"""Checkpoint benchmark: async-save overlap and elastic restore time.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The async save path splits a checkpoint into a blocking half (device-to-
host shard fetch at the step boundary) and a background half
(serialization, file writes, fsync, commit).  The number that matters to
a training run is how long the STEP LOOP is blocked — so this measures,
on the same sharded pytree:

- sync_save_ms:    full blocking save (stage + write + commit inline)
- async_blocked_ms: how long save() holds the caller before returning
                    (the background writer still runs to completion and
                    is timed separately as write_ms)
- restore_ms:      committed-directory restore onto the current mesh

`vs_baseline` is sync_save_ms / async_blocked_ms — the factor by which
the step-boundary stall shrinks when I/O moves off-thread.  The written
bytes are identical and every async save is verified COMMITTED, so the
speedup is pure overlap, not skipped work.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time


def _build_tree(size_mb: int):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("data",))
    n = len(jax.devices())
    per = max(1, size_mb // 4)
    rows = per * (1 << 20) // (256 * 4)
    rows -= rows % n    # shard dim must divide evenly across the mesh
    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    tree = {
        "params": {
            f"layer{i}": jax.device_put(
                np.random.default_rng(i).standard_normal(
                    (rows, 256), dtype=np.float32), sh)
            for i in range(4)},
        "scale": jax.device_put(
            np.arange(256, dtype=np.float32), rep),
        "step": 0,
    }
    nbytes = 4 * rows * 256 * 4 + 256 * 4
    return mesh, tree, nbytes


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size-mb", type=int, default=64,
                    help="approximate checkpoint payload size")
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    from ray_tpu.checkpoint import (
        AsyncCheckpointer, restore_sharded, save_sharded)

    mesh, tree, nbytes = _build_tree(args.size_mb)
    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        # Warmup: first save pays directory creation + allocator ramp.
        save_sharded(os.path.join(root, "warm"), tree)

        sync_times = []
        for r in range(args.repeats):
            path = os.path.join(root, f"sync{r}")
            t0 = time.perf_counter()
            save_sharded(path, tree, step=r)
            sync_times.append(time.perf_counter() - t0)

        ckptr = AsyncCheckpointer()
        blocked_times, write_times = [], []
        for r in range(args.repeats):
            path = os.path.join(root, f"async{r}")
            t0 = time.perf_counter()
            handle = ckptr.save(path, tree, step=r)
            blocked_times.append(time.perf_counter() - t0)
            handle.wait(120)
            write_times.append(time.perf_counter() - t0)
            assert handle.committed()

        restore_times = []
        for r in range(args.repeats):
            t0 = time.perf_counter()
            out = restore_sharded(os.path.join(root, "sync0"), mesh=mesh)
            import jax
            jax.block_until_ready(out["params"]["layer0"])
            restore_times.append(time.perf_counter() - t0)

        sync_ms = statistics.median(sync_times) * 1e3
        blocked_ms = statistics.median(blocked_times) * 1e3
        write_ms = statistics.median(write_times) * 1e3
        restore_ms = statistics.median(restore_times) * 1e3
        print(json.dumps({
            "metric": "ckpt_async_blocked_ms",
            "value": round(blocked_ms, 2),
            "unit": "ms",
            "vs_baseline": round(sync_ms / blocked_ms, 2),
            "sync_save_ms": round(sync_ms, 2),
            "async_write_total_ms": round(write_ms, 2),
            "restore_ms": round(restore_ms, 2),
            "payload_mb": round(nbytes / (1 << 20), 1),
            "sync_write_mb_s": round(nbytes / (1 << 20)
                                     / (sync_ms / 1e3), 1),
            "overlap_fraction": round(1.0 - blocked_ms / write_ms, 3),
            "repeats": args.repeats,
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
