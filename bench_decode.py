"""Decode benchmark: GPT-2-small continuous-batching throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Measures aggregate steady-state decode tokens/s with the paged-KV
continuous-batching engine at 32 concurrent sequences, and the same
engine serving one sequence at a time.  `vs_baseline` is the ratio —
the speedup continuous batching buys over sequential decoding.  Decode
is weight-streaming-bound, so one 32-lane step costs roughly one
1-lane step and the ratio should approach the lane count (the
acceptance bar is >= 5x).
"""

from __future__ import annotations

import argparse
import json
import time


def _decode_tps(engine, n_seqs, prompt_len, new_tokens, *, sequential):
    """Aggregate generated-tokens/s over n_seqs requests."""
    prompts = [[(7 * i + j) % engine.config.vocab_size
                for j in range(prompt_len)] for i in range(n_seqs)]
    t0 = time.perf_counter()
    if sequential:
        for p in prompts:
            engine.generate(p, max_new_tokens=new_tokens)
    else:
        handles = [engine.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        while engine.step():
            pass
        for h in handles:
            h.tokens()
    dt = time.perf_counter() - t0
    return n_seqs * new_tokens / dt


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="gpt2-small")
    ap.add_argument("--lanes", type=int, default=32)
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=48)
    ap.add_argument("--seq-probe", type=int, default=2,
                    help="sequences timed for the sequential baseline")
    args = ap.parse_args()

    from ray_tpu.inference import InferenceEngine

    max_seq_len = args.prompt_len + args.new_tokens + 16
    engine = InferenceEngine(
        "gpt", args.config, max_lanes=args.lanes, block_size=16,
        max_seq_len=max_seq_len, prefill_chunk=args.prompt_len,
        auto_start=False)

    # Warmup: compile both step shapes (prefill chunk + pure decode).
    engine.generate([1] * args.prompt_len, max_new_tokens=4)

    batched_tps = _decode_tps(engine, args.lanes, args.prompt_len,
                              args.new_tokens, sequential=False)
    seq_tps = _decode_tps(engine, args.seq_probe, args.prompt_len,
                          args.new_tokens, sequential=True)

    print(json.dumps({
        "metric": "gpt2_decode_tokens_per_sec",
        "value": round(batched_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(batched_tps / seq_tps, 3),
        "lanes": args.lanes,
        "sequential_tokens_per_sec": round(seq_tps, 1),
    }))


if __name__ == "__main__":
    main()
