"""Train-plane preemption benchmark: goodput with grace-window saves.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the same fixed-step training job twice on a single-node cluster
while a scripted `chaos_preempt_at` maintenance event delivers a
preemption notice (with a grace window) mid-run: once with only sparse
periodic checkpoints (a "blind" restart resumes from the last periodic
save, replaying everything since), and once with a
`session.set_preemption_hook` grace-window rescue that checkpoints the
in-flight step inside the window (resume loses at most that step).
Reports the grace-save goodput in steps/s; `vs_baseline` is the ratio
over the blind-restart goodput.  Steps replayed and the measured
time-to-recovery (from the train_recovery_seconds histogram) ride
along so the win's mechanism is visible.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


def _loop(config):
    import numpy as np

    from ray_tpu.train import session

    mgr = session.get_checkpoint_manager()
    holder = {}
    if config["grace_save"]:
        def rescue(remaining_s):
            h = mgr.save(holder["step"], dict(holder["state"]))
            h._event.wait(30)
        session.set_preemption_hook(rescue)
    start = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        start = int(ckpt.to_dict()["step"]) + 1
    for step in range(start, config["steps"]):
        holder["step"] = step
        holder["state"] = {"w": np.full((64, 64), float(step)),
                           "step": step}
        if step % config["ckpt_every"] == 0:
            h = mgr.save(step, dict(holder["state"]))
            h._event.wait(30)
        time.sleep(config["step_s"])
        session.report({"step": step, "resumed_from": start})


def _run_mode(args, grace_save: bool):
    """One cluster lifetime: train through the scripted preemption and
    return per-mode stats (wall_s, steps_replayed, recovery_s, ...)."""
    import ray_tpu
    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import DataParallelTrainer
    from ray_tpu.util import metrics as mt

    root = tempfile.mkdtemp(prefix="bench_train_ft_")
    ray_tpu.init(num_cpus=2, object_store_memory=64 << 20, _system_config={
        "chaos_enabled": True,
        "chaos_seed": args.seed,
        "chaos_preempt_at": args.preempt_at,
        "chaos_preempt_target": "head",
        "chaos_preempt_grace_s": args.grace_s,
    })
    tag = {"reason": "preempted"}
    # Copy: read() hands back the registry's live dict, and the
    # registry outlives the cluster, so "after" would alias "before".
    before = dict(mt.read("train_recovery_seconds", tag) or
                  {"count": 0.0, "sum": 0.0})
    try:
        trainer = DataParallelTrainer(
            _loop,
            train_loop_config={"grace_save": grace_save,
                               "steps": args.steps,
                               "step_s": args.step_s,
                               "ckpt_every": args.ckpt_every},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="bench", storage_path=root,
                failure_config=FailureConfig(max_failures=3)))
        t0 = time.perf_counter()
        result = trainer.fit()
        wall = time.perf_counter() - t0
        if result.error is not None:
            raise result.error
        history = result.metrics_history
        resumes = sorted({m["resumed_from"] for m in history})
        replayed = 0
        if len(resumes) > 1:
            # Steps executed by the first incarnation: everything it
            # reported plus the one aborted at the notice boundary; the
            # resume point decides how many of those were kept.
            inc1_last = max(m["step"] for m in history
                            if m["resumed_from"] == resumes[0])
            replayed = max(0, (inc1_last + 2) - resumes[1])
        after = mt.read("train_recovery_seconds", tag) or before
        n_rec = after["count"] - before["count"]
        recovery = ((after["sum"] - before["sum"]) / n_rec) if n_rec else 0.0
        return {"wall_s": wall, "steps_replayed": replayed,
                "recovery_s": recovery, "resumes": resumes,
                "last_step": result.metrics.get("step"),
                "n_history": len(history),
                "completed": result.metrics["step"] == args.steps - 1,
                "preempted": n_rec > 0}
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.invalidate_cache()
        fi.reset()
        shutil.rmtree(root, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--step-s", type=float, default=0.4,
                    help="simulated compute per train step")
    ap.add_argument("--ckpt-every", type=int, default=10,
                    help="periodic checkpoint interval (steps)")
    ap.add_argument("--preempt-at", type=int, default=7,
                    help="scripted preemption at this hostd heartbeat tick")
    ap.add_argument("--grace-s", type=float, default=3.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--mode", choices=["blind", "grace"], default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.mode is not None:
        print(json.dumps(_run_mode(args, grace_save=args.mode == "grace")))
        return

    # Each mode runs in a fresh interpreter: the scripted preemption tick
    # is wall-clock-anchored to hostd boot, and a warm second in-process
    # run boots ~2s faster — shifting which step the notice lands on and
    # making the modes incomparable.
    def run(mode):
        cmd = [sys.executable, os.path.abspath(__file__), "--mode", mode,
               "--steps", str(args.steps), "--step-s", str(args.step_s),
               "--ckpt-every", str(args.ckpt_every),
               "--preempt-at", str(args.preempt_at),
               "--grace-s", str(args.grace_s), "--seed", str(args.seed)]
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if p.returncode != 0:
            raise SystemExit(f"{mode} mode failed:\n{p.stderr[-2000:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    blind = run("blind")
    grace = run("grace")

    goodput_blind = args.steps / max(blind["wall_s"], 1e-9)
    goodput_grace = args.steps / max(grace["wall_s"], 1e-9)

    print(json.dumps({
        "metric": "train_preempt_goodput",
        "value": round(goodput_grace, 3),
        "unit": "steps_per_s",
        "vs_baseline": round(goodput_grace / max(goodput_blind, 1e-9), 3),
        "goodput_blind_restart": round(goodput_blind, 3),
        "steps_replayed_grace_save": grace["steps_replayed"],
        "steps_replayed_blind_restart": blind["steps_replayed"],
        "recovery_s_grace_save": round(grace["recovery_s"], 2),
        "recovery_s_blind_restart": round(blind["recovery_s"], 2),
        "wall_s_grace_save": round(grace["wall_s"], 2),
        "wall_s_blind_restart": round(blind["wall_s"], 2),
        "preempted_both_modes": blind["preempted"] and grace["preempted"],
        "steps": args.steps,
        "grace_s": args.grace_s,
    }))


if __name__ == "__main__":
    main()
