"""MPMD pipeline benchmark: bubble fraction + tokens/s vs the dryrun.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the same 4-stage tanh-MLP pipeline two ways on the same microbatch
schedule:

- **dryrun** — the single-program GPipe schedule in
  `parallel/pipeline.py` (ppermute rotation inside one XLA program over
  a `stage=4` mesh of forced-host CPU devices);
- **mpmd** — the fault-tolerant MPMD trainer
  (`train/pipeline_trainer.py`): one actor gang per stage, activations
  crossing stages as objects over the shm transfer plane.

The headline number is forward tokens/s for MPMD with `vs_baseline` the
ratio over the dryrun; the MPMD train-step bubble fraction (from full
1F1B fwd+bwd+update steps) and the fwd-loss parity check ride along.

Honesty notes (single host): every "stage" here is a process on ONE
machine, so the dryrun's ppermute is a memcpy and the MPMD transfer
plane is shm-to-shm — neither pays real ICI/DCN latency, and the
dryrun's whole-schedule XLA fusion gives it an advantage that shrinks
with real per-stage compute.  The MPMD path's value on this box is the
robustness contract (per-stage restart), not throughput; treat the
ratio as overhead accounting, not a scaling claim.

Each mode runs in a fresh interpreter: the dryrun needs
XLA_FLAGS=--xla_force_host_platform_device_count=8 set before jax
imports, and the MPMD mode must not inherit 8 fake devices per stage
worker.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

D = 32
N_STAGES = 4
SEED = 7


def _params(n_micro, micro_b):
    import numpy as np
    rng = np.random.default_rng(SEED)
    params = [{"w": rng.normal(0, 0.3, (D, D)), "b": np.zeros(D)}
              for _ in range(N_STAGES)]
    xs = [rng.normal(size=(micro_b, D)) for _ in range(n_micro)]
    ts = [rng.normal(size=(micro_b, D)) * 0.1 for _ in range(n_micro)]
    return params, xs, ts


def _run_dryrun(args):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.parallel import (MeshConfig, create_mesh,
                                  pipeline_loss_dryrun, stack_stage_params)

    params, xs, ts = _params(args.n_micro, args.micro_batch)
    mesh = create_mesh(MeshConfig(data=2, stage=N_STAGES))
    stacked = stack_stage_params(
        [{"w": jnp.asarray(p["w"]), "b": jnp.asarray(p["b"])}
         for p in params])
    mb = jnp.asarray(np.stack(xs))
    tg = jnp.asarray(np.stack(ts))

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    fn = jax.jit(lambda sp, m, t: pipeline_loss_dryrun(
        stage_fn, loss_fn, mesh, sp, m, t))
    loss = float(fn(stacked, mb, tg))            # compile + parity value
    t0 = time.perf_counter()
    for _ in range(args.reps):
        fn(stacked, mb, tg).block_until_ready()
    wall = time.perf_counter() - t0
    rows = args.reps * args.n_micro * args.micro_batch
    return {"loss": loss, "fwd_tokens_per_s": rows / wall,
            "wall_s": wall}


def _run_mpmd(args):
    import numpy as np

    import ray_tpu
    from ray_tpu.train import PipelineTrainer, jax_stage_fns

    def stage_fn(p, x):
        import jax.numpy as jnp
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y, t):
        import jax.numpy as jnp
        return jnp.mean((y - t) ** 2)

    params, xs, ts = _params(args.n_micro, args.micro_batch)
    ray_tpu.init(num_cpus=N_STAGES + 2, object_store_memory=256 << 20)
    tr = PipelineTrainer(
        jax_stage_fns(stage_fn, loss_fn), params, lr=0.05,
        n_microbatches=args.n_micro, schedule="1f1b",
        queue_depth=args.queue_depth, interleave=args.interleave,
        prefetch=bool(args.prefetch))
    loss = tr.forward_only(xs, ts)               # warm workers + parity
    t0 = time.perf_counter()
    for _ in range(args.reps):
        tr.forward_only(xs, ts)
    wall = time.perf_counter() - t0
    rows = args.reps * args.n_micro * args.micro_batch

    hist = tr.fit(lambda step: (xs, ts), args.train_steps)
    bubble = float(np.mean([h["bubble_fraction"] for h in hist]))
    step_s = float(np.mean([h["wall_s"] for h in hist]))
    tr.shutdown()
    ray_tpu.shutdown()
    return {"loss": loss, "fwd_tokens_per_s": rows / wall, "wall_s": wall,
            "bubble_fraction": bubble, "train_step_s": step_s,
            "gangs": N_STAGES // args.interleave}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-micro", type=int, default=8)
    ap.add_argument("--micro-batch", type=int, default=16)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--train-steps", type=int, default=5)
    ap.add_argument("--queue-depth", type=int, default=2)
    ap.add_argument("--interleave", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--prefetch", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--mode", choices=["dryrun", "mpmd"], default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.mode == "dryrun":
        print(json.dumps(_run_dryrun(args)))
        return
    if args.mode == "mpmd":
        print(json.dumps(_run_mpmd(args)))
        return

    def run(mode, interleave=1, prefetch=0):
        cmd = [sys.executable, os.path.abspath(__file__), "--mode", mode,
               "--n-micro", str(args.n_micro),
               "--micro-batch", str(args.micro_batch),
               "--reps", str(args.reps),
               "--train-steps", str(args.train_steps),
               "--queue-depth", str(args.queue_depth),
               "--interleave", str(interleave),
               "--prefetch", str(prefetch)]
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != 0:
            raise SystemExit(f"{mode} mode failed:\n{p.stderr[-2000:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    dryrun = run("dryrun")
    # Round-15 baseline row first, then the two overlap levers: pre-push
    # alone (same 4 gangs), then interleave v=2 + pre-push (2 gangs each
    # owning 2 non-adjacent chunks).
    mpmd_modes = [
        ("baseline_1f1b", 1, 0),
        ("prepush", 1, 1),
        ("interleaved_prepush", 2, 1),
    ]
    modes = {}
    for name, v, pf in mpmd_modes:
        r = run("mpmd", interleave=v, prefetch=pf)
        # The loss-exactness gate, per mode: same params, same math.
        drift = abs(r["loss"] - dryrun["loss"])
        tol = 1e-5 * max(1.0, abs(dryrun["loss"]))
        if drift > tol:
            raise SystemExit(
                f"{name}: MPMD loss {r['loss']} != dryrun loss "
                f"{dryrun['loss']} (drift {drift:.3e} > tol {tol:.3e})")
        modes[name] = {
            "fwd_tokens_per_s": round(r["fwd_tokens_per_s"], 1),
            "bubble_fraction": round(r["bubble_fraction"], 4),
            "train_step_s": round(r["train_step_s"], 4),
            "gangs": r["gangs"],
            "loss_drift": drift,
        }

    mpmd = modes["interleaved_prepush"]
    print(json.dumps({
        "metric": "pp_mpmd_fwd_tokens_per_s",
        "value": modes["prepush"]["fwd_tokens_per_s"],
        "unit": "rows_per_s",
        "vs_baseline": round(modes["prepush"]["fwd_tokens_per_s"]
                             / max(dryrun["fwd_tokens_per_s"], 1e-9), 4),
        "dryrun_fwd_tokens_per_s": round(dryrun["fwd_tokens_per_s"], 1),
        "bubble_fraction": mpmd["bubble_fraction"],
        "bubble_fraction_baseline": modes["baseline_1f1b"][
            "bubble_fraction"],
        "train_step_s": mpmd["train_step_s"],
        "modes": modes,
        "loss_dryrun": dryrun["loss"],
        "stages": N_STAGES,
        "n_micro": args.n_micro,
        "micro_batch": args.micro_batch,
        "schedule": "1f1b",
        "single_host_caveat": "all stages on one machine; shm transfers, "
                              "no ICI/DCN — overhead accounting, not a "
                              "scaling claim",
    }))


if __name__ == "__main__":
    main()
