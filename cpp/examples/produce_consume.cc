// Example: a C++ producer feeding a ray_tpu store.
//
// Build:
//   g++ -std=c++17 -I cpp/include produce_consume.cc \
//       ray_tpu/_native/objstore.cc -pthread -o produce_consume
// Run with a store path printed by `ray_tpu.init()` / the hostd logs:
//   ./produce_consume /dev/shm/ray_tpu_store_xxx
//
// The Python side reads the object zero-copy:
//   ray_tpu.get(ObjectRef-from-id)  /  ObjectStore.attach(path).get(id)

#include <cstdio>
#include <vector>

#include <ray_tpu/store_client.hpp>

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <shm store path>\n", argv[0]);
    return 2;
  }
  auto store = ray_tpu::Store::attach(argv[1]);

  // Write a 1 MiB tensor directly into shared memory (one copy total).
  ray_tpu::ObjectId id = ray_tpu::ObjectId::random();
  const uint64_t n = 1 << 20;
  uint8_t* dst = store.create(id, n);
  for (uint64_t i = 0; i < n; i++) dst[i] = uint8_t(i & 0xff);
  store.seal(id);
  std::printf("produced object (1 MiB), id bytes written\n");

  // Read it back zero-copy.
  auto buf = store.get(id, 1000);
  std::printf("read back %llu bytes, first=%d last=%d\n",
              static_cast<unsigned long long>(buf.size()),
              buf.data()[0], buf.data()[n - 1]);
  return 0;
}
