// ray_tpu C++ embedding API — zero-copy object-store client.
//
// Reference parity: the role of cpp/include/ray/api.h (the reference's C++
// API lets native programs produce/consume cluster objects).  Scope here
// (recorded in STATUS.md): C++ programs embed as DATA-PLANE peers — they
// attach to a node's shared-memory object store and exchange zero-copy
// buffers with Python tasks on the same node (native data loaders,
// feature pipelines, sensor ingest).  Task submission from C++ rides the
// typed-proto control plane as that migration completes; it is NOT part
// of this header yet.
//
// Usage:
//   #include <ray_tpu/store_client.hpp>
//   auto store = ray_tpu::Store::attach("/dev/shm/ray_tpu_store_...");
//   ray_tpu::ObjectId id = ray_tpu::ObjectId::random();
//   store.put(id, data, size);               // visible to Python ray_tpu
//   auto buf = store.get(id, /*timeout_ms=*/1000);   // zero-copy view
//
// Link against lib tpustore.so (built by ray_tpu/_native, or compile
// objstore.cc into your binary).

#pragma once

#include <cstdint>
#include <cstring>
#include <random>
#include <stdexcept>
#include <string>

extern "C" {
int tpus_attach(const char* path, void** out);
void tpus_close(void* h);
int tpus_obj_create(void* h, const uint8_t* id, uint64_t data_size,
                    uint64_t meta_size, uint64_t* data_off);
int tpus_obj_seal(void* h, const uint8_t* id);
int tpus_obj_abort(void* h, const uint8_t* id);
int tpus_obj_get(void* h, const uint8_t* id, int64_t timeout_ms,
                 uint64_t* data_off, uint64_t* data_size,
                 uint64_t* meta_size);
int tpus_obj_release(void* h, const uint8_t* id);
int tpus_obj_contains(void* h, const uint8_t* id);
unsigned char* tpus_base(void* h);
}

namespace ray_tpu {

constexpr int kObjectIdSize = 28;  // ids.py ObjectID: 24B task + 4B index

struct ObjectId {
  uint8_t bytes[kObjectIdSize];

  static ObjectId random() {
    ObjectId id{};
    std::random_device rd;
    std::mt19937_64 gen(rd());
    for (int i = 0; i < kObjectIdSize; i += 8) {
      uint64_t v = gen();
      std::memcpy(id.bytes + i,
                  &v, std::min(8, kObjectIdSize - i));
    }
    return id;
  }

  static ObjectId from_binary(const std::string& b) {
    if (b.size() != kObjectIdSize)
      throw std::invalid_argument("ObjectId needs 28 bytes");
    ObjectId id{};
    std::memcpy(id.bytes, b.data(), kObjectIdSize);
    return id;
  }

  std::string binary() const {
    return std::string(reinterpret_cast<const char*>(bytes),
                       kObjectIdSize);
  }
};

class Store;

// Zero-copy read view; releases its refcount on destruction.
class ObjectBuffer {
 public:
  ObjectBuffer(ObjectBuffer&& o) noexcept
      : store_(o.store_), id_(o.id_), data_(o.data_), size_(o.size_),
        meta_(o.meta_), meta_size_(o.meta_size_) {
    o.store_ = nullptr;
  }
  ObjectBuffer(const ObjectBuffer&) = delete;
  ~ObjectBuffer();

  const uint8_t* data() const { return data_; }
  uint64_t size() const { return size_; }
  const uint8_t* metadata() const { return meta_; }
  uint64_t metadata_size() const { return meta_size_; }

 private:
  friend class Store;
  ObjectBuffer(void* store, ObjectId id, const uint8_t* data,
               uint64_t size, const uint8_t* meta, uint64_t meta_size)
      : store_(store), id_(id), data_(data), size_(size), meta_(meta),
        meta_size_(meta_size) {}
  void* store_;
  ObjectId id_;
  const uint8_t* data_;
  uint64_t size_;
  const uint8_t* meta_;
  uint64_t meta_size_;
};

class Store {
 public:
  static Store attach(const std::string& shm_path) {
    void* h = nullptr;
    int rc = tpus_attach(shm_path.c_str(), &h);
    if (rc != 0)
      throw std::runtime_error("ray_tpu: attach failed rc=" +
                               std::to_string(rc));
    return Store(h);
  }

  Store(Store&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  Store(const Store&) = delete;
  ~Store() {
    if (h_) tpus_close(h_);
  }

  // Copy-in put.  For large producers prefer create()/seal() and write
  // into the returned pointer directly (single copy total).
  void put(const ObjectId& id, const void* data, uint64_t size,
           const void* meta = nullptr, uint64_t meta_size = 0) {
    uint8_t* dst = create(id, size, meta_size);
    std::memcpy(dst, data, size);
    if (meta_size) std::memcpy(dst + size, meta, meta_size);
    seal(id);
  }

  // Reserve an unsealed buffer; write into it, then seal().
  uint8_t* create(const ObjectId& id, uint64_t size,
                  uint64_t meta_size = 0) {
    uint64_t off = 0;
    int rc = tpus_obj_create(h_, id.bytes, size, meta_size, &off);
    if (rc != 0)
      throw std::runtime_error("ray_tpu: create failed rc=" +
                               std::to_string(rc));
    return tpus_base(h_) + off;
  }

  void seal(const ObjectId& id) {
    if (tpus_obj_seal(h_, id.bytes) != 0)
      throw std::runtime_error("ray_tpu: seal failed");
  }

  void abort(const ObjectId& id) { tpus_obj_abort(h_, id.bytes); }

  bool contains(const ObjectId& id) {
    return tpus_obj_contains(h_, id.bytes) == 1;
  }

  // Blocking zero-copy get; timeout_ms < 0 waits forever.
  ObjectBuffer get(const ObjectId& id, int64_t timeout_ms = -1) {
    uint64_t off = 0, size = 0, msize = 0;
    int rc = tpus_obj_get(h_, id.bytes, timeout_ms, &off, &size, &msize);
    if (rc != 0)
      throw std::runtime_error("ray_tpu: get failed rc=" +
                               std::to_string(rc));
    const uint8_t* base = tpus_base(h_) + off;
    return ObjectBuffer(h_, id, base, size, base + size, msize);
  }

 private:
  explicit Store(void* h) : h_(h) {}
  void* h_;
};

inline ObjectBuffer::~ObjectBuffer() {
  if (store_) tpus_obj_release(store_, id_.bytes);
}

}  // namespace ray_tpu
