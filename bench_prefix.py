"""Prefix-cache benchmark: shared-system-prompt TTFT, cold vs warm.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Real serving traffic is dominated by shared prefixes (system prompts,
few-shot templates, multi-turn history).  This measures time-to-first-
token for a prompt of `--prefix-len` shared tokens plus a `--suffix-len`
unique tail, two ways on the SAME engine: warm (the shared prefix is
sealed in the content-addressed block index, admission adopts it by
reference and prefills only the tail) and cold (a never-seen prefix —
every token prefills from scratch).  `vs_baseline` is cold_ttft /
warm_ttft — the speedup prefix caching buys; with the default shapes
the cached prefix covers ~94% of the prompt's blocks and the acceptance
bar is >= 5x.  Decode tokens/s is reported for both phases to show the
steady-state path is untouched.
"""

from __future__ import annotations

import argparse
import itertools
import json
import statistics
import time

_uid = itertools.count(1)


def _measure(engine, prompt, new_tokens):
    """(ttft_seconds, decode_tokens_per_sec) for one request, driving
    the scheduler manually so TTFT is not hostage to thread wakeups."""
    h = engine.submit(prompt, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    while h._req.out.qsize() == 0:
        engine.step()
    ttft = time.perf_counter() - t0
    t1 = time.perf_counter()
    while engine.step():
        pass
    toks = h.tokens(timeout=60)
    decode_dt = time.perf_counter() - t1
    tps = (len(toks) - 1) / decode_dt if decode_dt > 0 else float("inf")
    return ttft, tps


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="gpt2-small")
    ap.add_argument("--prefix-len", type=int, default=512,
                    help="shared system-prompt length (tokens)")
    ap.add_argument("--suffix-len", type=int, default=32,
                    help="unique per-request tail length (tokens)")
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    args = ap.parse_args()

    from ray_tpu.inference import InferenceEngine

    total = args.prefix_len + args.suffix_len
    engine = InferenceEngine(
        "gpt", args.config, max_lanes=4, block_size=args.block_size,
        max_seq_len=total + args.new_tokens + args.block_size,
        prefill_chunk=args.prefill_chunk, auto_start=False)
    vocab = engine.config.vocab_size

    def tail(n):
        return [(13 * next(_uid) + j) % vocab for j in range(n)]

    system_prompt = [(3 * j + 1) % vocab for j in range(args.prefix_len)]

    # Warmup compiles both step shapes AND seals the shared prefix.
    engine.generate(system_prompt + tail(args.suffix_len), max_new_tokens=2)

    # Warm first: cold runs below seal their own (unique) prefixes and
    # under pool pressure would LRU-evict the shared one.
    warm = [_measure(engine, system_prompt + tail(args.suffix_len),
                     args.new_tokens) for _ in range(args.repeats)]
    cold = [_measure(engine, tail(args.prefix_len) + tail(args.suffix_len),
                     args.new_tokens) for _ in range(args.repeats)]

    warm_ttft = statistics.median(t for t, _ in warm)
    cold_ttft = statistics.median(t for t, _ in cold)
    stats = engine.stats()
    hit_blocks = args.prefix_len // args.block_size
    total_blocks = -(-total // args.block_size)

    print(json.dumps({
        "metric": "gpt2_prefix_warm_ttft_ms",
        "value": round(warm_ttft * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(cold_ttft / warm_ttft, 2),
        "cold_ttft_ms": round(cold_ttft * 1e3, 2),
        "warm_decode_tokens_per_sec":
            round(statistics.median(r for _, r in warm), 1),
        "cold_decode_tokens_per_sec":
            round(statistics.median(r for _, r in cold), 1),
        "prefix_len": args.prefix_len,
        "suffix_len": args.suffix_len,
        "hit_block_fraction": round(hit_blocks / total_blocks, 3),
        "prefix_hit_tokens": stats["prefix_hit_tokens"],
        "blocks_evicted": stats["blocks_evicted"],
    }))


if __name__ == "__main__":
    main()
