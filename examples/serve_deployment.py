"""Serve a model behind HTTP (reference: serve quickstart)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=2)
class Scorer:
    def __init__(self, scale: float):
        self.scale = scale

    async def __call__(self, payload):
        # async handlers overlap on the replica's persistent event loop
        return {"score": self.scale * float(payload["value"])}


def main():
    ray_tpu.init(num_cpus=4)
    handle = serve.run(Scorer.bind(2.5))
    port = serve.start(with_proxy=True)

    # Python-handle path:
    print(handle.remote({"value": 4.0}).result(timeout=30))

    # HTTP path (route = deployment name):
    import json
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/Scorer",
        data=json.dumps({"value": 10}).encode(),
        headers={"Content-Type": "application/json"})
    print(json.loads(urllib.request.urlopen(req, timeout=30).read()))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
