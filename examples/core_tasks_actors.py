"""Core runtime tour: tasks, actors, the object store, placement groups."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ray_tpu


@ray_tpu.remote
def preprocess(shard):
    return shard * 2.0


@ray_tpu.remote
class ParameterHolder:
    def __init__(self):
        self.version = 0
        self.params = np.zeros(4)

    def update(self, grad):
        self.params = self.params - 0.1 * grad
        self.version += 1
        return self.version

    def get(self):
        return self.params


def main():
    ray_tpu.init(num_cpus=4)
    # Parallel tasks over object-store shards (zero-copy for numpy).
    shards = [ray_tpu.put(np.full(4, float(i))) for i in range(8)]
    outs = ray_tpu.get([preprocess.remote(s) for s in shards])
    print("task fan-out:", [float(o[0]) for o in outs])

    # A stateful actor consuming task outputs.
    holder = ParameterHolder.remote()
    for o in outs:
        holder.update.remote(o)
    print("actor state after 8 updates:", ray_tpu.get(holder.get.remote()))

    # Placement groups reserve resources atomically.
    from ray_tpu.util import placement_group
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=30)
    print("placement group ready:", pg.bundle_specs)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
