"""Data pipeline: ingest -> transform -> streaming consumption
(reference: data quickstart — the executor streams across operators with
bounded in-flight windows)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu import data as rd


def main():
    ray_tpu.init(num_cpus=4)
    ds = (rd.range(10_000, parallelism=8)
          .map(lambda row: {"id": row["id"], "x": row["id"] * 0.5})
          .filter(lambda row: row["id"] % 2 == 0))
    # Streaming consumption: blocks flow through the pipeline with
    # backpressure; nothing materializes the whole dataset.
    total = 0.0
    for batch in ds.iter_batches(batch_size=1024):   # dict of columns
        total += float(batch["x"].sum())
    print(f"sum(x) over even ids = {total}")
    # All-to-all ops are barriers:
    print("sorted head:", ds.sort("x", descending=True).take(3))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
