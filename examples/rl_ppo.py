"""Train PPO on CartPole with the RL stack (reference: rllib quickstart)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu.rllib.ppo import PPOConfig


def main():
    ray_tpu.init(num_cpus=4)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=8)
            .training(train_batch_size=2048)
            .debugging(seed=0)
            .build())
    try:
        for _ in range(10):
            r = algo.train()
            print(f"iter {r['training_iteration']}: "
                  f"reward_mean={r['episode_reward_mean']:.1f}")
    finally:
        algo.stop()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
