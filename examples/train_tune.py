"""A Tune sweep over a training loop (reference: tune quickstart).
Swap the toy objective for a JaxTrainer to sweep real model training."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu import tune


def train_fn(config):
    # Stand-in for a model training loop reporting per-epoch metrics.
    w = 0.0
    for epoch in range(8):
        w += config["lr"] * (1.0 - w)           # converges toward 1
        loss = (1.0 - w) ** 2 + 0.01 / config["batch"]
        tune.report({"loss": loss, "epoch": epoch})


def main():
    ray_tpu.init(num_cpus=4)
    tuner = tune.Tuner(
        train_fn,
        param_space={"lr": tune.loguniform(1e-3, 1.0),
                     "batch": tune.choice([16, 32, 64])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=12,
            scheduler=tune.ASHAScheduler(max_t=8, grace_period=2)),
    )
    best = tuner.fit().get_best_result()
    print("best loss:", best.metrics["loss"],
          "config:", best.metrics.get("config"))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
