"""Async actor/learner RL (Podracer) on CartPole: a rollout gang runs
ahead of a stale-tolerant V-trace learner, weights publish in place
through the object plane every update."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu.rl import PodracerConfig


def main():
    ray_tpu.init(num_cpus=4)
    algo = (PodracerConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                      rollout_fragment_length=32)
            .training(staleness_bound=2, publish_interval=1,
                      min_updates_per_step=2, lr=1e-3)
            .debugging(seed=0)
            .build())
    try:
        for _ in range(15):
            r = algo.train()
            print(f"iter {r['training_iteration']}: "
                  f"reward_mean={r['episode_reward_mean']:.1f} "
                  f"version={r['policy_version']} "
                  f"updates={r['learner_updates_total']} "
                  f"staleness={r.get('learner/staleness', 0.0):.0f} "
                  f"dropped={r['queue']['stale_dropped']}")
    finally:
        algo.stop()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
