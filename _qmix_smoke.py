import time
from ray_tpu.rllib import QMixConfig, VDNConfig

def run(cfg_cls, iters=40):
    cfg = cfg_cls()
    cfg.seed = 0
    algo = cfg.build()
    t0 = time.time()
    for i in range(iters):
        algo.train()
    g = algo.evaluate_greedy()
    print(cfg.mixer, "greedy team return:", g, f"({time.time()-t0:.0f}s)")
    return g

q = run(QMixConfig)
v = run(VDNConfig)
print("RESULT qmix", q, "vdn", v)
