"""Speculative decoding benchmark: accepted-tokens/step + tokens/s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the continuous-batching engine twice over the same repetitive-text
workload — once plain (one token per jitted step) and once with
self-speculative n-gram drafting (`spec_k` drafts verified per step) —
at 1/8/32 concurrent lanes, and reports per-lane-count tokens/s, the
speedup ratio, accepted-tokens-per-verify-step, and decode TBT p50/p99
from the engine's SLO histograms (bucket-count deltas around each run,
so the two configurations don't pollute each other).

Repetitive text is speculation's home turf: code, templated prose and
multi-turn transcripts make the n-gram proposer's lookups land, so
acceptance approaches spec_k and per-step overhead (dispatch, host
scheduling, sampling commit) amortizes over several tokens.  The
headline row (value / vs_baseline / accepted_per_step) is the
single-lane latency regime — the regime speculative decoding targets,
where each decode step is overhead-bound and a T=k+1 verify costs
barely more than a T=1 step; the bar there is accepted-tokens/step
> 1.5 and a tokens/s speedup >= 1.3x.  Higher lane counts are reported
alongside (and their TBT p50 still drops) but on a compute-saturated
device the verify step's extra B*T positions cost real FLOPs, so the
aggregate-throughput win shrinks as batch grows — the classic reason
serving stacks gate speculation on batch occupancy.
"""

from __future__ import annotations

import argparse
import gc
import json
import time


def _prompts(n_seqs, prompt_len, period, vocab):
    """Cyclic token streams (distinct phase/alphabet per sequence), the
    stand-in for repetitive text."""
    return [[(i * 17 + (j % period)) % vocab for j in range(prompt_len)]
            for i in range(n_seqs)]


def _tbt_snapshot():
    from ray_tpu.util import metrics
    snap = metrics.collect().get("inference_tbt_s")
    if not snap or not snap["series"]:
        return None, []
    return snap, list(snap["series"][0]["value"]["buckets"])


def _tbt_quantiles(before):
    """p50/p99 of the TBT observations made since `before` (bucket-count
    delta against the current snapshot)."""
    from ray_tpu.util import metrics
    snap, counts = _tbt_snapshot()
    if snap is None:
        return float("nan"), float("nan")
    delta = [c - b for c, b in zip(counts, before + [0] * len(counts))]
    q = metrics.quantiles_from_buckets(snap["buckets"], delta,
                                       qs=(0.5, 0.99))
    return q[0.5], q[0.99]


def _run(engine, prompts, new_tokens):
    """Aggregate generated-tokens/s plus the TBT p50/p99 of this run.

    Cycle-collector pauses are excluded (collect, then disable for the
    timed region — the same hygiene ``timeit`` applies): a single gen-2
    sweep is tens of ms, an order of magnitude over the per-step cost
    being measured, and it lands on whichever run crosses the
    allocation threshold rather than on the slower engine."""
    _, before = _tbt_snapshot()
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        handles = [engine.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        while engine.step():
            pass
        for h in handles:
            assert len(h.tokens()) == new_tokens
        dt = time.perf_counter() - t0
    finally:
        gc.enable()
    p50, p99 = _tbt_quantiles(before)
    return len(prompts) * new_tokens / dt, p50, p99


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="nano",
                    help="model config (nano keeps the number tracking "
                    "per-step overhead, the thing speculation amortizes)")
    ap.add_argument("--lanes", default="1,8,32")
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--period", type=int, default=4,
                    help="token period of the repetitive workload")
    ap.add_argument("--new-tokens", type=int, default=96)
    ap.add_argument("--spec-k", type=int, default=4)
    args = ap.parse_args()

    from ray_tpu.inference import InferenceEngine

    lane_counts = [int(x) for x in args.lanes.split(",")]
    max_seq_len = args.prompt_len + args.new_tokens + args.spec_k + 16
    rows = []
    params = None
    for lanes in lane_counts:
        plain = InferenceEngine(
            "gpt", args.config, params, max_lanes=lanes, block_size=16,
            max_seq_len=max_seq_len, prefill_chunk=args.prompt_len,
            auto_start=False, seed=0)
        params = plain.params
        spec = InferenceEngine(
            "gpt", args.config, params, max_lanes=lanes, block_size=16,
            max_seq_len=max_seq_len, prefill_chunk=args.prompt_len,
            auto_start=False, seed=0, spec_k=args.spec_k)
        prompts = _prompts(lanes, args.prompt_len, args.period,
                           plain.config.vocab_size)
        # Warmup: compile every step shape — prefill + T=1 via a short
        # generate, then the T=1 fallback and each verify width the
        # engine may dispatch (T=2..spec_k+1: the step is sized to the
        # widest draft actually proposed) via empty fully-masked
        # batches.  A short warmup generate is not guaranteed to draft,
        # and a mid-run compile would land a ~0.5s stall inside the
        # timed region.
        plain.generate(prompts[0], max_new_tokens=4)
        spec.generate(prompts[0], max_new_tokens=4)
        spec._run_step(spec._build_batch([], 1)[0])
        for t in range(2, args.spec_k + 2):
            spec._run_step(spec._build_batch([], t)[0], True)

        plain_tps, pp50, pp99 = _run(plain, prompts, args.new_tokens)
        spec_tps, sp50, sp99 = _run(spec, prompts, args.new_tokens)
        st = spec.stats()
        sample = spec.generate(prompts[0], args.new_tokens)
        assert sample == plain.generate(prompts[0], args.new_tokens), \
            "speculative output diverged from the plain engine"
        rows.append({
            "lanes": lanes,
            "plain_tokens_per_sec": round(plain_tps, 1),
            "spec_tokens_per_sec": round(spec_tps, 1),
            "speedup": round(spec_tps / plain_tps, 3),
            "accepted_per_step": round(st["spec_accepted_per_step"], 3),
            "plain_tbt_p50_ms": round(pp50 * 1e3, 3),
            "plain_tbt_p99_ms": round(pp99 * 1e3, 3),
            "spec_tbt_p50_ms": round(sp50 * 1e3, 3),
            "spec_tbt_p99_ms": round(sp99 * 1e3, 3),
        })
        plain.shutdown()
        spec.shutdown()

    # Headline = the lowest lane count (the latency regime speculation
    # targets); the full by_lanes table keeps the saturation curve
    # honest.
    top = min(rows, key=lambda r: r["lanes"])
    print(json.dumps({
        "metric": "spec_decode_tokens_per_sec",
        "value": top["spec_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": top["speedup"],
        "accepted_per_step": top["accepted_per_step"],
        "spec_k": args.spec_k,
        "config": args.config,
        "new_tokens": args.new_tokens,
        "by_lanes": rows,
    }))


if __name__ == "__main__":
    main()
