"""Podracer RL substrate benchmark: engine-backed rollout throughput,
publish wall, learner steps/s vs staleness bound.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}
and writes the full document to RL_BENCH.json.

Three measurements, one async-RL story:

1. Rollout tokens/s, speculative decoding ON vs OFF at fixed hardware
   (same nano model, same repetitive-prompt workload, greedy).  Spec
   decoding is token-exact, so on the rollout path it is a pure
   throughput multiplier over an UNCHANGED behavior policy — the bar is
   >= 1.2x at 1 lane (the overhead-bound regime), with the multi-lane
   row alongside.  Exactness is asserted, not assumed: the spec
   rollout's action tokens must equal the plain rollout's.

2. Publish wall as a fraction of rollout wall at the bench shape: a
   2-actor remote gang generates through real engines while the driver
   publishes a fresh weight version (one put + gang-wide adopt, wait
   for adoption) every round.  The bar is publish < 10% of rollout —
   in-place adoption by reference must be noise next to generation.

3. Learner steps/s vs staleness bound k on the CartPole loop: k=0
   forces on-policy (fragments racing a publish are dropped), larger k
   lets the learner train whatever the gang delivers.  The curve is the
   price of freshness — updates/s should rise from k=0 to k>=1.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import time


def _prompts(n, prompt_len, period, vocab):
    return [[(i * 17 + (j % period)) % vocab for j in range(prompt_len)]
            for i in range(n)]


def _make_actor(spec_k, lanes, args, params):
    from ray_tpu.rl import EngineRolloutActor
    return EngineRolloutActor(
        "gpt", args.config, params=params, max_lanes=lanes,
        spec_k=spec_k, temperature=0.0, seed=0, block_size=16,
        max_seq_len=args.prompt_len + args.new_tokens + args.spec_k + 16,
        prefill_chunk=args.prompt_len)


def _warm(actor, prompts, spec_k):
    """Compile outside the timed region: prefill + T=1 via a short
    rollout, then every verify width spec may dispatch."""
    actor.rollout(prompts[:1], max_new_tokens=4)
    eng = actor.engine
    if spec_k:
        eng._run_step(eng._build_batch([], 1)[0])
        for t in range(2, spec_k + 2):
            eng._run_step(eng._build_batch([], t)[0], True)


def _timed_rollout(actor, prompts, new_tokens):
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        batch, _version, metrics = actor.rollout(prompts, new_tokens)
        dt = time.perf_counter() - t0
    finally:
        gc.enable()
    return metrics["tokens"] / dt, batch


def bench_rollout_spec(args):
    rows = []
    params = None
    for lanes in (1, 4):
        plain = _make_actor(0, lanes, args, params)
        params = plain.engine.params
        spec = _make_actor(args.spec_k, lanes, args, params)
        prompts = _prompts(lanes, args.prompt_len, args.period,
                           plain.engine.config.vocab_size)
        _warm(plain, prompts, 0)
        _warm(spec, prompts, args.spec_k)
        plain_tps, pb = _timed_rollout(plain, prompts, args.new_tokens)
        spec_tps, sb = _timed_rollout(spec, prompts, args.new_tokens)
        assert (sb["actions"] == pb["actions"]).all(), \
            "speculative rollout diverged from the plain behavior policy"
        st = spec.engine.stats()
        rows.append({
            "lanes": lanes,
            "plain_tokens_per_sec": round(plain_tps, 1),
            "spec_tokens_per_sec": round(spec_tps, 1),
            "speedup": round(spec_tps / plain_tps, 3),
            "accepted_per_step": round(st["spec_accepted_per_step"], 3),
        })
        plain.engine.shutdown()
        spec.engine.shutdown()
    return rows


def bench_publish_vs_rollout(args):
    import ray_tpu
    from ray_tpu.rl import EngineRolloutActor, WeightPublisher

    remote_cls = ray_tpu.remote(num_cpus=1)(EngineRolloutActor)
    actors = [remote_cls.remote(
        "gpt", args.config, max_lanes=args.gang_lanes, spec_k=args.spec_k,
        temperature=0.0, seed=i, block_size=16,
        max_seq_len=args.prompt_len + args.new_tokens + args.spec_k + 16,
        prefill_chunk=args.prompt_len) for i in range(args.gang_size)]
    prompts = _prompts(args.gang_lanes, args.prompt_len, args.period, 256)
    # Warmup round compiles each remote engine (and its spec widths via
    # the first drafted steps) outside the timed loop.
    ray_tpu.get([a.rollout.remote(prompts, args.new_tokens)
                 for a in actors])
    # Publish real params: build one local engine for the payload tree.
    from ray_tpu.rl.rollout import EngineRolloutActor as _Local
    local = _Local("gpt", args.config, max_lanes=1, temperature=0.0,
                   seed=0)
    weights = local.engine.params
    publisher = WeightPublisher()
    rollout_wall = publish_wall = 0.0
    tokens = 0
    for round_i in range(args.rounds):
        t0 = time.perf_counter()
        publisher.publish(weights, actors, version=round_i + 1, wait=True)
        publish_wall += time.perf_counter() - t0
        t0 = time.perf_counter()
        out = ray_tpu.get([a.rollout.remote(prompts, args.new_tokens)
                           for a in actors])
        rollout_wall += time.perf_counter() - t0
        tokens += sum(m["tokens"] for _b, _v, m in out)
        for _b, v, _m in out:
            assert v == round_i + 1, "gang missed a version boundary"
    local.engine.shutdown()
    return {
        "gang_size": args.gang_size,
        "rounds": args.rounds,
        "rollout_tokens_per_sec": round(tokens / rollout_wall, 1),
        "rollout_wall_s": round(rollout_wall, 3),
        "publish_wall_s": round(publish_wall, 3),
        "publish_frac_of_rollout": round(publish_wall / rollout_wall, 4),
    }


def bench_learner_vs_staleness(args):
    from ray_tpu.rl import PodracerConfig
    rows = []
    for k in (0, 1, 2):
        cfg = (PodracerConfig().environment("CartPole-v1")
               .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                         rollout_fragment_length=32)
               .training(staleness_bound=k, publish_interval=1,
                         min_updates_per_step=2)
               .debugging(seed=0))
        algo = cfg.build()
        try:
            for _ in range(2):   # spawn + compile outside the window
                algo.train()
            u0 = algo.learner.num_updates
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < args.learner_window_s:
                r = algo.train()
            dt = time.perf_counter() - t0
            st = r["queue"]
            rows.append({
                "staleness_bound": k,
                "updates_per_sec": round(
                    (algo.learner.num_updates - u0) / dt, 2),
                "stale_dropped": st["stale_dropped"],
                "accepted": st["accepted"],
            })
        finally:
            algo.stop()
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="nano")
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--period", type=int, default=4)
    ap.add_argument("--new-tokens", type=int, default=96)
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--gang-size", type=int, default=2)
    ap.add_argument("--gang-lanes", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--learner-window-s", type=float, default=6.0)
    args = ap.parse_args()

    import ray_tpu

    spec_rows = bench_rollout_spec(args)
    ray_tpu.init(num_cpus=max(4, args.gang_size + 2),
                 object_store_memory=128 << 20)
    try:
        pub = bench_publish_vs_rollout(args)
        learner_rows = bench_learner_vs_staleness(args)
    finally:
        ray_tpu.shutdown()

    top = next(r for r in spec_rows if r["lanes"] == 1)
    doc = {
        "metric": "rl_rollout_spec_tokens_per_sec",
        "value": top["spec_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": top["speedup"],
        "accepted_per_step": top["accepted_per_step"],
        "spec_k": args.spec_k,
        "config": args.config,
        "new_tokens": args.new_tokens,
        "rollout_by_lanes": spec_rows,
        "publish": pub,
        "learner_by_staleness_bound": learner_rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "RL_BENCH.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
