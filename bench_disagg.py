"""Disaggregated-serving benchmark: prefill/decode split vs monolithic.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}
and writes the full document to DISAGG_BENCH.json.

Three measurements, one claim each:

1. **TTFT/TBT/goodput, equal hardware.**  The same multi-client
   shared-prefix streaming workload runs against 2 monolithic
   LLMDeployment replicas and against 1 prefill + 1 decode replica
   (serve/kv_tier).  Monolithic p2c routing splits each group's prefix
   across both replica caches — a request landing on the "wrong"
   replica re-prefills the whole shared prefix, and that prefill
   interleaves into the same engine loop its neighbours are decoding
   through.  Disaggregation concentrates ALL prefill (and the prefix
   cache) on the prefill replica and ships sealed blocks to the decode
   replica, so `vs_baseline` for TTFT p99 is monolithic/disagg (>1
   means the split wins).

2. **Prefix hit-rate with/without the spill tier.**  One engine with a
   device pool too small for the working set replays a prompt cycle;
   with a KVTierCache attached, evicted chains restore from host/store
   instead of re-prefilling.  The claim is strictly-higher hit rate.

3. **Token-exactness through the handoff.**  Greedy AND seeded-sampled
   output through export -> codec -> import equals a monolithic
   engine's, asserted (not just reported).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _prompts(args):
    """`requests` prompts in `groups` shared-prefix groups: a long
    shared head (the disaggregation target) + a short unique tail."""
    out = []
    for i in range(args.requests):
        g = i % args.groups
        head = [1 + ((g * 13 + t) % 96) for t in range(args.prefix_len)]
        out.append(head + [100 + i % 150, 101 + i % 150, 1 + i % 96])
    return out


def _drive(stream_fn, prompts, budget, concurrency):
    """Fire the workload; returns (ttfts, tbts, wall_s, tokens_out)."""
    ttfts, tbts = [], []
    tokens_out = [0]
    lock = threading.Lock()
    it = iter(list(enumerate(prompts)))

    def worker():
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            _i, prompt = nxt
            t0 = time.perf_counter()
            last = None
            got = 0
            for _tok in stream_fn(prompt, budget):
                now = time.perf_counter()
                if last is None:
                    with lock:
                        ttfts.append(now - t0)
                else:
                    with lock:
                        tbts.append(now - last)
                last = now
                got += 1
            with lock:
                tokens_out[0] += got

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return ttfts, tbts, time.perf_counter() - t0, tokens_out[0]


def _teardown():
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.serve import _private as sp
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()
    with sp._router_states_lock:
        sp._router_states.clear()
    GLOBAL_CONFIG.invalidate_cache()


def run_monolithic(args):
    """Equal hardware baseline: 2 monolithic replicas behind p2c."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import llm_stream_resume

    ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    serve.start()
    try:
        handle = serve.run(serve.LLMDeployment.options(
            name="llm_mono_bench", num_replicas=2).bind(
                model="gpt", config="nano", max_lanes=args.concurrency,
                seed=0)).options("generate", failover=llm_stream_resume)
        list(handle.stream([1, 2, 3], 2))            # compile both shapes
        return _drive(lambda p, b: handle.stream(p, b),
                      _prompts(args), args.budget, args.concurrency)
    finally:
        _teardown()


def run_disagg(args):
    """1 prefill + 1 decode replica — same chip count as the baseline."""
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, object_store_memory=128 << 20)
    serve.start()
    try:
        handle = serve.run_disaggregated(
            model="gpt", config="nano", max_lanes=args.concurrency,
            seed=0, name="llm_disagg_bench")
        list(handle.stream([1, 2, 3], 2))            # compile both engines
        return _drive(handle.stream,
                      _prompts(args), args.budget, args.concurrency)
    finally:
        _teardown()


def run_hit_rate(with_tier: bool):
    """Prefix hit rate over a working set larger than the device pool;
    the spill tier turns second-pass evictions back into hits."""
    from ray_tpu.inference import InferenceEngine
    from ray_tpu.serve.kv_tier import KVTierCache

    eng = InferenceEngine("gpt", "nano", seed=0, auto_start=False,
                          num_blocks=8, block_size=16)
    if with_tier:
        eng.cache.attach_tier(KVTierCache(host_blocks=16,
                                          store_blocks=32))
    prompts = [list(range(s, s + 48)) for s in
               (1, 60, 120, 180, 240, 300)]
    for _cycle in range(2):
        for p in prompts:
            eng.generate(p, 4)
    st = eng.stats()
    hit, miss = st["prefix_hit_tokens"], st["prefix_miss_tokens"]
    return hit / max(1, hit + miss), st


def check_token_exact():
    """Greedy + seeded equality through export -> codec -> import."""
    from ray_tpu.inference import InferenceEngine
    from ray_tpu.serve.kv_tier import KVBlockCodec

    prompt = list(range(1, 49))
    prefill = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
    prefill.prefill(prompt).tokens()
    blob = KVBlockCodec.encode(prefill.export_prefix(prompt))
    results = {}
    for name, temp, seed in (("greedy", 0.0, None), ("seeded", 0.8, 7)):
        decode = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
        mono = InferenceEngine("gpt", "nano", seed=0, auto_start=False)
        decode.import_prefix(KVBlockCodec.decode(blob))
        got = decode.generate(prompt, 12, temperature=temp, seed=seed)
        ref = mono.generate(prompt, 12, temperature=temp, seed=seed)
        assert got == ref, f"{name} handoff output diverged: {got} != {ref}"
        results[name] = True
    return results


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=36)
    ap.add_argument("--groups", type=int, default=6)
    ap.add_argument("--prefix-len", type=int, default=96)
    ap.add_argument("--budget", type=int, default=8)
    ap.add_argument("--concurrency", type=int, default=6)
    args = ap.parse_args()

    exact = check_token_exact()

    rate_cold, _ = run_hit_rate(with_tier=False)
    rate_tier, st_tier = run_hit_rate(with_tier=True)
    assert rate_tier > rate_cold, (
        f"spill tier did not raise hit rate: {rate_tier} <= {rate_cold}")

    mono_ttft, mono_tbt, mono_wall, mono_toks = run_monolithic(args)
    dis_ttft, dis_tbt, dis_wall, dis_toks = run_disagg(args)

    mono_p99 = _percentile(mono_ttft, 0.99)
    dis_p99 = _percentile(dis_ttft, 0.99)
    doc = {
        "metric": "disagg_ttft_p99_ms",
        "value": round(dis_p99 * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(mono_p99 / max(dis_p99, 1e-9), 3),
        "monolithic_ttft_p99_ms": round(mono_p99 * 1000, 1),
        "ttft_p50_ms": {
            "monolithic": round(_percentile(mono_ttft, 0.5) * 1000, 1),
            "disagg": round(_percentile(dis_ttft, 0.5) * 1000, 1)},
        "tbt_p99_ms": {
            "monolithic": round(_percentile(mono_tbt, 0.99) * 1000, 1),
            "disagg": round(_percentile(dis_tbt, 0.99) * 1000, 1)},
        "goodput_tok_s": {
            "monolithic": round(mono_toks / mono_wall, 1),
            "disagg": round(dis_toks / dis_wall, 1)},
        "prefix_hit_rate": {
            "no_tier": round(rate_cold, 4),
            "spill_tier": round(rate_tier, 4),
            "tier_restored_blocks": st_tier.get(
                "kv_tier_restored_blocks", 0)},
        "token_exact": exact,
        "requests": args.requests,
        "groups": args.groups,
        "prefix_len": args.prefix_len,
        "budget": args.budget,
        "concurrency": args.concurrency,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "DISAGG_BENCH.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
