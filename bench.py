"""Headline benchmark: GPT-2-125M train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (the reference publishes no model-throughput numbers —
BASELINE.json "published" is empty): the north star is >=90% of Ray-on-
A100+NCCL throughput.  An A100 fine-tuning GPT-2-125M in bf16 at a strong
40% MFU does 0.4 * 312e12 / (6 * 124e6) ~= 168k tokens/s/chip; 90% of that
= 151k tokens/s is the bar `vs_baseline` is normalised against, scaled by
the ratio of this chip's peak bf16 FLOPs to A100's so the number is
hardware-comparable.
"""

from __future__ import annotations

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt

    cfg = gpt.CONFIGS["gpt2-small"]
    batch, seq = 24, 1024    # b24 fastest per-token after the block/chunk
                             # retune (PERF.md round-2 sweep)

    init_state, train_step = gpt.make_train_step(cfg, optax.adamw(1e-4))
    state = init_state(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                cfg.vocab_size)
    step = jax.jit(train_step, donate_argnums=0)

    # Warmup (compile) then steady-state timing.  Synchronise by fetching
    # the loss value: on the tunneled TPU platform block_until_ready can
    # return before execution finishes, but a host transfer cannot.
    for _ in range(2):
        state, metrics = step(state, {"tokens": tokens})
    float(metrics["loss"])

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, {"tokens": tokens})
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * n_steps / dt

    # Peak bf16 TFLOPs for the local chip generation (vs A100's 312).
    peaks = {"v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v4": 275e12,
             "v6": 918e12}
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in peaks.items() if k in kind), 197e12)
    a100_bar = 0.9 * 0.4 * 312e12 / (6 * gpt.num_params(cfg))
    bar = a100_bar * (peak / 312e12)

    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / bar, 3),
    }))


if __name__ == "__main__":
    main()
