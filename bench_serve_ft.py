"""Serve failover benchmark: request survival under replica chaos.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the same streamed-generation workload twice against an
LLMDeployment while probabilistic `chaos_kill_replica` randomly
`os._exit(1)`s replicas mid-stream: once with no failover policy
(replica death surfaces to the caller) and once with the
`llm_stream_resume` policy (the handle resubmits with the produced
tokens appended to the prompt).  Reports the with-failover success
rate; `vs_baseline` is the ratio over the no-failover success rate —
how many requests failover rescues.  p99 latency for both modes rides
along so the healing cost is visible.
"""

from __future__ import annotations

import argparse
import json
import time
from concurrent.futures import ThreadPoolExecutor


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _run_mode(args, failover):
    """One cluster lifetime: deploy, fire the workload, tear down.

    Returns (successes, failures, per-request latencies in seconds)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.serve import _private as sp

    ray_tpu.init(num_cpus=4, _system_config={
        "chaos_enabled": True,
        "chaos_seed": args.seed,
        "chaos_kill_replica": args.kill_p,
    })
    serve.start()
    try:
        app = serve.LLMDeployment.options(
            name="llm_ft_bench", num_replicas=args.replicas).bind(
                model="gpt", config="nano", max_lanes=4, seed=0)
        handle = serve.run(app).options("generate", failover=failover)
        # Warmup (compiles the step shapes on each replica before timing).
        for _ in range(args.replicas):
            try:
                list(handle.stream([1, 2, 3], 2))
            except Exception:
                pass

        latencies, outcomes = [], []

        def one(i):
            prompt = [(5 * i + j) % 50 + 1 for j in range(4)]
            t0 = time.perf_counter()
            try:
                toks = list(handle.stream(prompt, args.new_tokens))
                ok = len(toks) == args.new_tokens
            except Exception:
                ok = False
            latencies.append(time.perf_counter() - t0)
            outcomes.append(ok)

        with ThreadPoolExecutor(max_workers=4) as pool:
            list(pool.map(one, range(args.requests)))
        return sum(outcomes), len(outcomes) - sum(outcomes), latencies
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
            with sp._router_states_lock:
                sp._router_states.clear()
            GLOBAL_CONFIG.invalidate_cache()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=12)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--kill-p", type=float, default=0.02,
                    help="per-serve-event replica kill probability")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    from ray_tpu.serve.llm import llm_stream_resume

    ok_plain, fail_plain, lat_plain = _run_mode(args, failover=None)
    ok_fo, fail_fo, lat_fo = _run_mode(args, failover=llm_stream_resume)

    rate_plain = ok_plain / max(1, ok_plain + fail_plain)
    rate_fo = ok_fo / max(1, ok_fo + fail_fo)

    print(json.dumps({
        "metric": "serve_failover_success_rate",
        "value": round(rate_fo, 4),
        "unit": "fraction",
        "vs_baseline": round(rate_fo / max(rate_plain, 1e-9), 3),
        "success_rate_no_failover": round(rate_plain, 4),
        "p99_latency_ms_failover": round(
            _percentile(lat_fo, 0.99) * 1000, 1),
        "p99_latency_ms_no_failover": round(
            _percentile(lat_plain, 0.99) * 1000, 1),
        "requests": args.requests,
        "kill_p": args.kill_p,
    }))


if __name__ == "__main__":
    main()
