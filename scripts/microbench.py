"""Core runtime microbenchmarks.

Reference parity: python/ray/_private/ray_perf.py:93-305 (`ray
microbenchmark`) — put/get ops/s, task submit+get sync and pipelined,
1:1 actor calls sync and pipelined, async-actor calls.

Writes MICROBENCH.json at the repo root:
    {"<bench>": {"ops_s": N, "n": N}, ...}
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu  # noqa: E402

ROUNDS = 5


def timeit(name, fn, n, results, settle: float = 0.0):
    # Warmup round, then let background churn (frees, spills, worker
    # spawns) drain so sections don't pollute each other.  The committed
    # number is the MEDIAN of five timed rounds with the observed range
    # alongside — this host's run-to-run variance is ±25%, and a best-of
    # methodology on a bimodal distribution reports the lucky phase.
    fn(max(1, n // 10))
    if settle:
        time.sleep(settle)
    rates = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        fn(n)
        dt = time.perf_counter() - t0
        rates.append(n / dt)
    med = statistics.median(rates)
    results[name] = {"ops_s": round(med, 1), "n": n, "rounds": ROUNDS,
                     "min_ops_s": round(min(rates), 1),
                     "max_ops_s": round(max(rates), 1)}
    print(f"{name:32s} {med:10,.1f} ops/s   (median of {ROUNDS}x{n}, "
          f"range {min(rates):,.0f}-{max(rates):,.0f})")


def bench_checkpoint(results: dict):
    """Sharded-checkpoint microbenches: full sync save, the stage
    (device-to-host) half that is all an ASYNC save blocks the step loop
    for, and committed-directory restore.  16 MiB payload so the numbers
    track the checkpoint machinery, not disk bandwidth alone."""
    import shutil
    import tempfile

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.checkpoint import restore_sharded, save_sharded, sharded

    mesh = Mesh(np.array(jax.devices()), ("data",))
    rows = (16 << 20) // (256 * 4)
    rows -= rows % len(jax.devices())
    state = {"w": jax.device_put(np.zeros((rows, 256), np.float32),
                                 NamedSharding(mesh, P("data")))}
    root = tempfile.mkdtemp(prefix="microbench_ckpt_")
    try:
        path = os.path.join(root, "ck")

        def ckpt_save_sync(n):
            for _ in range(n):
                save_sharded(path, state)

        timeit("ckpt_save_sync_16MiB", ckpt_save_sync, 5, results)

        def ckpt_stage(n):
            for _ in range(n):
                sharded.stage(state)

        timeit("ckpt_stage_16MiB", ckpt_stage, 20, results)

        def ckpt_restore(n):
            for _ in range(n):
                jax.block_until_ready(
                    restore_sharded(path, mesh=mesh)["w"])

        timeit("ckpt_restore_16MiB", ckpt_restore, 10, results)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_serve(results: dict):
    """Serve fault-tolerance microbenches: a full mid-stream replica
    kill + failover-resume cycle, and a graceful drain-on-downscale
    cycle.  Both are wall-clock-per-recovery numbers (ops/s of whole
    heal cycles), so regressions in reconcile latency, drain polling,
    or the failover resubmit path all move them."""
    from ray_tpu import serve
    from ray_tpu.serve._private import CONTROLLER_NAME, SERVE_NAMESPACE

    serve.start()
    try:
        @serve.deployment(name="mb_failover", num_replicas=1)
        def chunks(n):
            for i in range(n):
                yield i

        handle = serve.run(chunks.bind()).options(failover="replay")
        assert list(handle.stream(4)) == list(range(4))  # warm replica
        controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)

        def failover_resume(n):
            # One op = stream 8 chunks, kill the replica after 2, let
            # the handle heal (controller respawns) + resume via replay.
            for _ in range(n):
                got = []
                for c in handle.stream(8):
                    got.append(c)
                    if len(got) == 2:
                        routing = ray_tpu.get(
                            controller.get_routing.remote("mb_failover"),
                            timeout=30)
                        ray_tpu.kill(routing["replicas"][0])
                assert got == list(range(8))

        timeit("serve_failover_resume", failover_resume, 3, results)
        serve.delete("mb_failover")

        @serve.deployment(name="mb_drain", num_replicas=1)
        def nopd():
            return 0

        def _wait(pred, timeout=30.0):
            deadline = time.perf_counter() + timeout
            while time.perf_counter() < deadline:
                if pred():
                    return
                time.sleep(0.05)
            raise TimeoutError("serve_drain wait timed out")

        serve.run(nopd.bind())

        def drain_cycle(n):
            # One op = scale 1->2 (wait both RUNNING), downscale 2->1,
            # wait until the victim fully drains out of the table.
            for _ in range(n):
                serve.run(nopd.options(num_replicas=2).bind())
                _wait(lambda: serve.status()["mb_drain"]["states"]
                      .get("RUNNING", 0) == 2)
                before = ray_tpu.get(
                    controller.drain_stats.remote(), timeout=30)
                serve.run(nopd.options(num_replicas=1).bind())
                _wait(lambda: ray_tpu.get(
                    controller.drain_stats.remote(), timeout=30)
                    ["drained_total"] > before["drained_total"])

        timeit("serve_drain", drain_cycle, 3, results)
        serve.delete("mb_drain")
    finally:
        serve.shutdown()


def bench_ingest(results: dict):
    """Input-pipeline microbenches: incremental batch assembly over
    misaligned Arrow blocks (the row-cursor path — batches/s), the
    overlapped device feed end to end (producer thread + double-buffered
    H2D — device batches/s), and the work-stealing coordinator's lease
    round-trip (leases/s: the per-block scheduling overhead a stealing
    split adds over a static split)."""
    import numpy as np

    from ray_tpu import data as rd
    from ray_tpu.data import block as blk
    from ray_tpu.data import ingest

    # Assembly: 64 blocks x 100 rows of a 256-wide float column, batch
    # size 96 deliberately misaligned so every batch crosses a boundary.
    blocks = [blk.batch_to_block(
        {"id": np.arange(i * 100, (i + 1) * 100),
         "x": np.ones((100, 256), np.float32)})
        for i in range(64)]

    def assemble(n):
        done = 0
        while done < n:
            for b in ingest.batches_from_block_iter(iter(blocks), 96):
                done += 1
                if done >= n:
                    break

    timeit("ingest_assemble", assemble, 400, results)

    # Device feed: partial drain (break at n) of the overlapped iterator
    # over a materialized dataset — covers block fetch, producer-thread
    # assembly, handoff queue, and the double-buffered device_put.
    ds = rd.range(4096, parallelism=8).materialize()
    it = ds.streaming_split(1)[0]

    def device_feed(n):
        done = 0
        while done < n:
            feed = it.iter_device_batches(batch_size=64)
            for _ in feed:
                done += 1
                if done >= n:
                    feed.close()
                    break

    timeit("ingest_device_feed", device_feed, 128, results)

    # Lease round-trip: one op = next() ack'ing the previous lease —
    # the steady-state coordinator hop per block.
    coord = ingest.SplitCoordinator.remote([list(range(100_000))])
    ray_tpu.get(coord.register.remote(0, []))

    def steal_lease(n):
        lease = None
        for _ in range(n):
            lease, _ = ray_tpu.get(coord.next.remote(0, lease))

    timeit("split_steal", steal_lease, 500, results)


def bench_train_ft(results: dict):
    """Train fault-tolerance microbenches: the preemption-notice step
    boundary (rescue save + commit + abort — the latency that must fit
    inside the grace window), and a gang down-shift cycle (full-size
    group torn down, smaller group re-formed: PG release, re-placement,
    actor spawn, worker boot) — the elastic resize-down path minus
    checkpoint replay."""
    import shutil
    import tempfile

    import numpy as np

    from ray_tpu.checkpoint import CheckpointManager
    from ray_tpu.exceptions import TrainPreemptedError
    from ray_tpu.train.session import TrainContext, _TrainSession
    from ray_tpu.train.worker_group import WorkerGroup

    root = tempfile.mkdtemp(prefix="microbench_train_ft_")
    state = {"w": np.zeros((256, 256), np.float32), "step": 0}
    ctx = TrainContext(world_rank=0, world_size=1, local_rank=0,
                       local_world_size=1, node_rank=0)
    ops = iter(range(10_000))
    try:
        def preempt_save(n):
            # One op = a notice-to-abort boundary on a live session: the
            # notice arms mid-step, the next report() runs the rescue
            # hook (durable 256 KiB save, wait for COMMIT) and aborts
            # with TrainPreemptedError.
            for _ in range(n):
                i = next(ops)
                mgr = CheckpointManager(root, save_id=f"mb{i}")
                box = {}

                def fn():
                    while True:
                        box["s"].report({"ok": 1})

                def rescue(remaining_s, mgr=mgr, i=i):
                    h = mgr.save(i, state)
                    if not h._event.wait(30):
                        raise TimeoutError("rescue save did not commit")

                sess = _TrainSession(fn, ctx)
                box["s"] = sess
                sess._preempt_hook = rescue
                sess.start()
                sess.get_next(timeout=10)          # first step delivered
                sess.notify_preemption(grace_s=5.0)
                try:
                    while sess.get_next(timeout=10) is not None:
                        pass
                    raise AssertionError("session ended without abort")
                except TrainPreemptedError:
                    pass
                mgr.wait_until_finished()

        timeit("train_preempt_save", preempt_save, 10, results)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    def resize_down(n):
        # One op = a down-shift cycle: form the full-size gang, tear it
        # down (lost node), re-form one worker smaller.
        for _ in range(n):
            wg2 = WorkerGroup(2, {"CPU": 1}, "PACK", pg_timeout_s=30.0)
            wg2.shutdown()
            wg1 = WorkerGroup(1, {"CPU": 1}, "PACK", pg_timeout_s=30.0)
            wg1.shutdown()

    timeit("train_resize_down", resize_down, 2, results, settle=1.0)


def bench_control_plane(results: dict):
    """Batched control-plane microbenches (PR 14).

    `batched_dispatch_burst`: drain rate of a one-shot 8k-task burst
    over held leases — the driver coalesces same-key specs into
    per-worker dispatch vectors, so this number moves with
    `sched_batch_max` and the vectorized result_seal path.

    `zygote_spawn_batch`: actors/s for an 8-actor storm where every
    actor needs a dedicated worker — each op pays lease, batched zygote
    fork (`zygote_spawn_parallelism` children per wakeup), boot, and
    first ping, then kills the actors so the next round forks fresh."""

    @ray_tpu.remote
    def nopc():
        return None

    def batched_dispatch(n):
        ray_tpu.get([nopc.remote() for _ in range(n)])

    batched_dispatch(2000)   # warm the lease pool past ramp-up
    timeit("batched_dispatch_burst", batched_dispatch, 8000, results,
           settle=1.0)

    @ray_tpu.remote
    class Spawn:
        def ping(self):
            return None

    def zygote_spawn(n):
        actors = [Spawn.remote() for _ in range(n)]
        ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
        for a in actors:
            ray_tpu.kill(a)

    timeit("zygote_spawn_batch", zygote_spawn, 8, results, settle=2.0)


def bench_observability(results: dict):
    """Observability hot-path costs: `events_append` is the per-record()
    overhead every instrumented plane pays (budget: < 5 µs/event, i.e.
    > 200k ops/s — the flight recorder must be cheap enough to leave on),
    `metrics_observe` is one bucketed-histogram observation (the SLO
    latency path: TTFT/TBT, queue wait, step time)."""
    from ray_tpu.util import events
    from ray_tpu.util import metrics as mt
    events.reset()

    def events_append(n):
        record = events.record
        for i in range(n):
            record("engine", "bench", i=i)

    timeit("events_append", events_append, 200_000, results)
    events.reset()

    h = mt.Histogram("microbench_observe_s", "observe() hot-path bench")

    def metrics_observe(n):
        obs = h.observe
        for i in range(n):
            obs(0.001 * (i & 1023))

    timeit("metrics_observe", metrics_observe, 200_000, results)

    # One durational span = one begin + one end = two ring slots.  The
    # budget is the same as two record() calls — a span edge must not
    # cost more than the instant events it replaces.
    from ray_tpu.util import spans

    def span_begin_end(n):
        begin, end = spans.begin, spans.end
        for i in range(n):
            end(begin("engine", "bench_span", i=i))

    timeit("span_begin_end", span_begin_end, 100_000, results)
    events.reset()

    # Reconstruction throughput: each op pairs/links a 1k-span chain
    # through the same build_spans path state.spans() uses, so the
    # reported rate is trees/s over a ring-sized stream.
    from ray_tpu import state as _state
    _evs = []
    for i in range(1000):
        sid, parent = f"{i:06x}", (f"{i - 1:06x}" if i else None)
        _evs.append({"ts": float(i), "ts_adj": float(i),
                     "plane": "engine", "kind": "bench_span",
                     "trace_id": "t1", "span_id": sid, "pid": 1,
                     "seq": 2 * i, "node_id": "n1", "source": "live",
                     "payload": {"ph": "B", "parent": parent}})
        _evs.append({"ts": i + 0.5, "ts_adj": i + 0.5, "plane": "engine",
                     "kind": "bench_span", "trace_id": "t1",
                     "span_id": sid, "pid": 1, "seq": 2 * i + 1,
                     "node_id": "n1", "source": "live",
                     "payload": {"ph": "E", "dur": 0.5}})

    def span_reconstruct(n):
        for _ in range(n):
            _state.build_spans(_evs, "t1")

    timeit("span_reconstruct_1k", span_reconstruct, 30, results)


def main():
    ray_tpu.init(num_cpus=8, object_store_memory=256 << 20)
    results: dict = {}

    # --- observability: flight recorder + histogram hot paths --------------
    bench_observability(results)

    # --- object store ------------------------------------------------------
    payload = b"x" * 100

    def put_small(n):
        for _ in range(n):
            ray_tpu.put(payload)

    timeit("put_small_100B", put_small, 2000, results)

    ref = ray_tpu.put(payload)

    def get_small(n):
        for _ in range(n):
            ray_tpu.get(ref)

    timeit("get_small_100B", get_small, 2000, results)

    import numpy as np
    big = np.zeros(1 << 20, np.uint8)  # 1 MiB

    def put_1mb(n):
        for _ in range(n):
            ray_tpu.put(big)

    timeit("put_1MiB", put_1mb, 500, results)
    time.sleep(3.0)  # drain the dropped-ref free/spill storm

    # --- tasks -------------------------------------------------------------
    @ray_tpu.remote
    def nop():
        return None

    def task_sync(n):
        for _ in range(n):
            ray_tpu.get(nop.remote())

    timeit("task_sync_roundtrip", task_sync, 300, results, settle=1.0)

    def task_pipelined(n):
        ray_tpu.get([nop.remote() for _ in range(n)])

    # Extra warmup: the first rounds also pay worker-pool ramp-up.
    task_pipelined(2000)
    timeit("task_pipelined", task_pipelined, 4000, results, settle=1.0)

    # --- actors ------------------------------------------------------------
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

    actor = Counter.remote()
    ray_tpu.get(actor.inc.remote())

    def actor_sync(n):
        for _ in range(n):
            ray_tpu.get(actor.inc.remote())

    timeit("actor_sync_roundtrip", actor_sync, 500, results)

    def actor_pipelined(n):
        ray_tpu.get([actor.inc.remote() for _ in range(n)])

    actor_pipelined(2000)
    timeit("actor_pipelined", actor_pipelined, 6000, results)

    @ray_tpu.remote
    class AsyncActor:
        async def ping(self):
            return 1

    aactor = AsyncActor.remote()
    ray_tpu.get(aactor.ping.remote())

    def async_actor_pipelined(n):
        ray_tpu.get([aactor.ping.remote() for _ in range(n)])

    timeit("async_actor_pipelined", async_actor_pipelined, 2000, results)

    # --- scaling: many concurrent tasks -----------------------------------
    # Fractional-CPU sleepers (reference ray_perf runs trivial tasks far
    # beyond core count): 0.25 CPU x 8-CPU node = 32 concurrent workers,
    # so 10ms tasks can overlap well past the core count and the measured
    # rate proves real overlap (serial would be 100/s).
    @ray_tpu.remote(num_cpus=0.25)
    def sleep10ms():
        time.sleep(0.01)
        return None

    def many_sleepers(n):
        ray_tpu.get([sleep10ms.remote() for _ in range(n)])

    # Steady-state measurement: the 32-worker pool ramps over a few
    # rounds (fork-server spawns + lease grants); a FIXED warmup keeps
    # ramp-up out of the number (reference ray_perf also measures the
    # warmed pool).  Median of five timed rounds with the range — rounds
    # on a 1-core host are bimodal, and a best-of methodology would
    # report the lucky phase (judged r4).  No settle sleep: the 1s lease
    # idle TTL would hand the warmed leases back mid-gap.
    for _ in range(3):
        many_sleepers(500)
    rates = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        many_sleepers(500)
        rates.append(500 / (time.perf_counter() - t0))
    med = statistics.median(rates)
    results["tasks_10ms_x500_concurrent"] = {
        "ops_s": round(med, 1), "n": 500, "rounds": ROUNDS,
        "min_ops_s": round(min(rates), 1),
        "max_ops_s": round(max(rates), 1)}
    print(f"{'tasks_10ms_x500_concurrent':32s} {med:10,.1f} ops/s   "
          f"(median of {ROUNDS}x500, range "
          f"{min(rates):,.0f}-{max(rates):,.0f})")

    # --- control plane: batched dispatch + zygote spawn --------------------
    bench_control_plane(results)

    # --- inference: continuous-batching decode step ------------------------
    # Steady-state decode-step rate of the paged-KV engine (nano model so
    # the number tracks scheduler + cache-update overhead, not matmul
    # time).  One step advances EVERY live lane, so aggregate tokens/s =
    # ops_s * lanes — the lane sweep shows how close a batched step stays
    # to a single-lane step (the continuous-batching win).
    from ray_tpu.inference import InferenceEngine

    for lanes in (1, 8, 32):
        eng = InferenceEngine("gpt", "nano", max_lanes=lanes, block_size=16,
                              prefill_chunk=8, auto_start=False)

        def decode_steps(n, eng=eng, lanes=lanes):
            # n+1 tokens = prefill-step sample + exactly n decode steps,
            # so every lane finishes inside the timed region (no drain
            # tail polluting the rate).
            for _ in range(lanes):
                eng.submit(list(range(8)), max_new_tokens=n + 1)
            eng.step()                    # prefill + first sampled token
            for _ in range(n):
                eng.step()

        timeit(f"decode_step_lanes{lanes}", decode_steps, 64, results)
        eng.shutdown()

    # --- inference: prefix-cache admission (prefill hit vs miss) -----------
    # Full request latency for a 112-token prompt whose first 96 tokens
    # are sealed in the content-addressed block index (admission adopts
    # them by reference; one chunk prefills) vs a never-seen prompt
    # (every chunk prefills).  The hit/miss ratio is the FLOP savings
    # prefix sharing buys on shared-system-prompt traffic — see
    # bench_prefix.py for the TTFT view at serving scale.
    import itertools
    uid = itertools.count(1)

    def _prefix_engine():
        return InferenceEngine("gpt", "nano", max_lanes=2, block_size=16,
                               num_blocks=64, prefill_chunk=32,
                               auto_start=False)

    eng = _prefix_engine()
    vocab = eng.config.vocab_size
    shared = [(3 * j + 1) % vocab for j in range(96)]
    eng.generate(shared + [5] * 16, max_new_tokens=1)  # seal the prefix

    def prefill_hit(n, eng=eng):
        for _ in range(n):
            tail = [(13 * next(uid) + j) % vocab for j in range(16)]
            eng.generate(shared + tail, max_new_tokens=1)

    timeit("prefill_hit", prefill_hit, 32, results)
    eng.shutdown()

    eng = _prefix_engine()

    def prefill_miss(n, eng=eng):
        for _ in range(n):
            p = [(13 * next(uid) + j) % vocab for j in range(112)]
            eng.generate(p, max_new_tokens=1)

    timeit("prefill_miss", prefill_miss, 32, results)
    eng.shutdown()

    # --- inference: speculative drafting + verify step ---------------------
    # spec_draft: host-side n-gram prompt-lookup over a 256-token
    # repetitive context — this runs per decode lane per step, so it
    # must stay orders of magnitude cheaper than a jitted step.
    # spec_verify: steady-state verify-dispatch rate (T=spec_k+1) of an
    # 8-lane speculative engine on cyclic text; aggregate tokens/s =
    # ops_s * lanes * accepted-per-step, so the number to compare with
    # decode_step_lanes8 is ops_s scaled by the acceptance multiplier.
    from ray_tpu.inference import NgramProposer

    proposer = NgramProposer()
    spec_ctx = [(j % 8) + 1 for j in range(256)]

    def spec_draft(n):
        for _ in range(n):
            proposer.propose(spec_ctx, 4)

    timeit("spec_draft", spec_draft, 20_000, results)

    eng = InferenceEngine("gpt", "nano", max_lanes=8, block_size=16,
                          prefill_chunk=8, auto_start=False, spec_k=4)

    def spec_verify(n, eng=eng):
        hs = [eng.submit([(j % 4) + 1 for j in range(8)],
                         max_new_tokens=5 * n + 8) for _ in range(8)]
        eng.step()                    # prefill + first sampled token
        for _ in range(n):
            eng.step()                # one verify dispatch per call
        for h in hs:
            h.cancel()

    timeit("spec_verify", spec_verify, 64, results)
    eng.shutdown()

    # --- data: ingest assembly / device feed / steal leases ----------------
    bench_ingest(results)

    # --- checkpoint: sharded save / stage / restore ------------------------
    bench_checkpoint(results)

    # --- serve: failover-resume + drain cycles -----------------------------
    bench_serve(results)

    # --- train: preempt-boundary rescue save + gang down-shift -------------
    bench_train_ft(results)

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MICROBENCH.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {out}")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
