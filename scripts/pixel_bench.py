"""IMPALA throughput at Atari frame shapes (reference: the role of
rllib/tuned_examples/ppo/atari-ppo.yaml — this image has no gym/ALE, so
the synthetic [84,84,4] env exercises the identical pixel pipeline:
uint8 frames -> rollout actors -> object store -> async learner thread
-> Nature-CNN V-trace SGD).  Gates on env-steps/sec, not reward.

Writes PIXEL_BENCH.json at the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu  # noqa: E402
from ray_tpu.rllib.impala import IMPALAConfig  # noqa: E402


def main():
    ray_tpu.init(num_cpus=4, object_store_memory=256 << 20)
    cfg = (IMPALAConfig()
           .environment("SyntheticPixel-v0")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                     rollout_fragment_length=16)
           .training(train_batch_size=0)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        algo.train()  # warmup: jit compiles, workers spawn
        t0 = time.perf_counter()
        steps0 = algo.total_env_steps
        updates0 = algo.learner.num_updates
        while time.perf_counter() - t0 < 20.0:
            algo.train()
        dt = time.perf_counter() - t0
        steps = algo.total_env_steps - steps0
        updates = algo.learner.num_updates - updates0
        result = {
            "env": "SyntheticPixel-v0 [84,84,4] uint8",
            "env_steps_per_s": round(steps / dt, 1),
            "learner_updates_per_s": round(updates / dt, 2),
            "window_s": round(dt, 1),
            "rollout_workers": 2,
            "envs_per_worker": 8,
        }
        print(json.dumps(result))
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PIXEL_BENCH.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {out}")
    finally:
        algo.stop()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
