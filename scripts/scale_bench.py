"""Scale-envelope benchmark (reference: release/benchmarks/README.md:9-31
— the reference's published envelope is 10k simultaneous tasks / 1M queued
tasks / 1k actors / multi-node object broadcast; this exercises the same
shapes against this runtime and records SCALE.json).

Sections
  queued_tasks          submit a deep backlog of trivial tasks, drain it
  concurrent_tasks_10k  10k no-op tasks in flight at once
  actor_storm           create as many actors as the host's RAM allows
                        (target 1k), ping them all, tear down
  broadcast_1gib        a large object written once, pulled by every other
                        node of a 4-hostd in-process cluster via the
                        native shm-to-shm plane

Sizes auto-scale down on small hosts (MemAvailable) — the applied size is
recorded in SCALE.json so a degraded run is never mistaken for the full
envelope.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu  # noqa: E402


def mem_available_bytes() -> int:
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemAvailable:"):
                return int(line.split()[1]) * 1024
    return 2 << 30


def bench_queued_tasks(results, n_queued: int):
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(2000)])  # warm pool
    time.sleep(1.0)
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n_queued)]
    submit_s = time.perf_counter() - t0
    ray_tpu.get(refs)
    total_s = time.perf_counter() - t0
    results["queued_tasks"] = {
        "n": n_queued,
        "submit_rate_per_s": round(n_queued / submit_s, 1),
        "drain_rate_per_s": round(n_queued / total_s, 1),
        "total_s": round(total_s, 2),
    }
    print(f"queued_tasks: {n_queued} queued, submit "
          f"{n_queued/submit_s:,.0f}/s, end-to-end {n_queued/total_s:,.0f}/s")


def bench_concurrent_tasks(results, n: int):
    @ray_tpu.remote(num_cpus=0.25)
    def hold():
        time.sleep(0.01)
        return None

    t0 = time.perf_counter()
    refs = [hold.remote() for _ in range(n)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    results["concurrent_tasks_10k"] = {
        "n": n, "total_s": round(dt, 2),
        "rate_per_s": round(n / dt, 1),
    }
    print(f"concurrent_tasks: {n} x 10ms tasks in {dt:.2f}s "
          f"({n/dt:,.0f}/s)")


def bench_actor_storm(results, target: int):
    # Each actor is one forked worker process; budget RAM for it AND
    # CPU.  Measured child-side floor on the CI host (PERF.md round-5):
    # zygote fork ~6ms + worker boot ~10ms CPU + creation ~2ms — on ONE
    # core that alone caps any storm near ~55/s, and past ~500 live
    # worker processes the shared gRPC/kernel layers destabilize
    # (observed cygrpc event-engine segfaults).  400 is the validated
    # stable envelope here; a 1000-actor storm belongs on a multi-core
    # cluster (the reference's envelope host).  The applied size is
    # recorded so a host-scaled run is never mistaken for the full
    # envelope.
    budget = int(mem_available_bytes() * 0.5 // (30 << 20))
    cpu_budget = max(400, (os.cpu_count() or 1) * 100)
    n = max(50, min(target, budget, cpu_budget))

    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return os.getpid()

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    refs = [a.ping.remote() for a in actors]
    ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=600)
    pids, failed = [], len(not_ready)
    ok = []
    for a, r in zip(actors, refs):
        if r in not_ready:
            continue
        try:
            pids.append(ray_tpu.get(r, timeout=30))
            ok.append(a)
        except Exception:
            failed += 1
    create_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for a in ok], timeout=600)
    ping_s = time.perf_counter() - t1
    for a in actors:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    results["actor_storm"] = {
        "n": n, "target": target, "created_ok": len(pids),
        "failed": failed, "distinct_workers": len(set(pids)),
        "create_and_first_ping_s": round(create_s, 2),
        "create_rate_per_s": round(len(pids) / create_s, 1),
        "steady_ping_rate_per_s": round(max(len(ok), 1) / ping_s, 1),
    }
    print(f"actor_storm: {len(pids)}/{n} actors (target {target}, "
          f"{failed} failed) in {create_s:.2f}s "
          f"({len(pids)/create_s:,.0f}/s), steady ping "
          f"{max(len(ok),1)/ping_s:,.0f}/s")


def bench_broadcast(results, size: int):
    """1 GiB-class object written on the driver's node, pulled by every
    other node store-to-store (native TCP plane)."""
    import numpy as np
    from ray_tpu.cluster_utils import Cluster

    per_node = int(size * 1.5)
    budget = int(mem_available_bytes() * 0.6)
    nodes = 4
    while nodes * per_node > budget and size > (64 << 20):
        size //= 2
        per_node = int(size * 1.5)
    cluster = Cluster(initialize_head=True, head_node_args={
        "num_cpus": 2, "object_store_memory": per_node})
    for _ in range(nodes - 1):
        cluster.add_node(num_cpus=2, object_store_memory=per_node)
    cluster.connect()
    try:
        @ray_tpu.remote(num_cpus=1)
        def fetch(ref_box, expect):
            arr = ray_tpu.get(ref_box[0])
            assert arr.nbytes == expect
            return float(arr[0]) + float(arr[-1])

        data = np.ones(size // 8, np.float64)
        ref = ray_tpu.put(data)
        t0 = time.perf_counter()
        # SPREAD forces distinct nodes so every pull crosses the plane.
        outs = ray_tpu.get([
            fetch.options(scheduling_strategy="SPREAD").remote(
                (ref,), data.nbytes)
            for _ in range(nodes - 1)], timeout=600)
        dt = time.perf_counter() - t0
        assert all(o == 2.0 for o in outs)
        gib = data.nbytes * (nodes - 1) / (1 << 30)
        results["broadcast_1gib"] = {
            "object_bytes": data.nbytes, "nodes": nodes,
            "total_moved_gib": round(gib, 3), "total_s": round(dt, 2),
            "gib_per_s": round(gib / dt, 3),
        }
        print(f"broadcast: {data.nbytes/(1<<30):.2f} GiB object to "
              f"{nodes-1} nodes in {dt:.2f}s ({gib/dt:.2f} GiB/s moved)")
    finally:
        cluster.shutdown()


def main():
    results: dict = {"host": {
        "cpus": os.cpu_count(),
        "mem_available_gib": round(mem_available_bytes() / (1 << 30), 2),
    }}
    # Single-node sections share one local cluster; the worker-pool cap
    # must clear the actor-storm target (default is 4x CPUs).
    ray_tpu.init(num_cpus=8, object_store_memory=256 << 20,
                 _system_config={"max_workers_per_node": 1200})
    bench_queued_tasks(results, n_queued=100_000)
    bench_concurrent_tasks(results, n=10_000)
    bench_actor_storm(results, target=1000)
    ray_tpu.shutdown()
    time.sleep(2)
    bench_broadcast(results, size=1 << 30)

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SCALE.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
