"""Critical-path attribution for the SCALE.json workloads.

Default mode re-runs `scale_bench.bench_queued_tasks`'s shape (warm
pool, burst submit, drain) under a trace, then slices the submit->drain
wall clock into lifecycle phases from the recorded spans and writes
SCALE_ATTRIB.json: per-phase attributed seconds, the top phases, and
the attribution coverage (the ISSUE gate: >= 90% of the gap named).

`python scripts/scale_attrib.py actor_storm` instead attributes the
actor-creation path: the spawn-side spans the hostd records without a
trace context (sched/zygote_fork, sched/worker_boot, proc/boot) are
scraped cluster-wide via state.events() + build_spans and unioned with
the driver-side lease/dispatch spans, so SCALE_ATTRIB.json shows where
an actor storm's wall clock goes (fork vs boot vs first ping vs lease
wait).  The result lands under an "actor_storm" key alongside the
queued-task attribution.

Attribution is a priority union-sweep, not a per-span sum: overlapping
spans (inflight covers ship->exec->reply; task covers arg_fetch/exec/
result_seal) would double-count, so each instant of wall clock is
charged to the highest-priority phase covering it — innermost phases
first, wrappers soak up only what their children left unexplained.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Burst-submitting N traced tasks writes ~6 span edges per task into the
# driver ring and ~8 into the executing worker's; size every ring so the
# earliest submits survive to the post-drain scrape.
N_TASKS = 20_000
os.environ.setdefault("RAY_TPU_EVENTS_RING_SIZE", str(1 << 18))

import ray_tpu  # noqa: E402
from ray_tpu import state  # noqa: E402
from ray_tpu.util import tracing  # noqa: E402

# Innermost first: a slice covered by exec belongs to exec even though
# dispatch/task also span it.
PHASE_PRIORITY = ("exec", "arg_fetch", "result_seal", "task", "dispatch",
                  "inflight", "sched_queue", "lease_wait", "submit",
                  "transfer")

# Actor-storm phases: spawn-path spans first (they are the storm's
# substance), then the generic task phases the first ping rides on.
# `exec` here IS the first ping (plus the trivial __init__ task) — the
# storm runs no other user code — so it is reported as `first_ping`.
ACTOR_PHASE_PRIORITY = ("zygote_fork", "exec", "arg_fetch", "result_seal",
                        "boot", "worker_boot", "task", "dispatch",
                        "inflight", "sched_queue", "lease_wait", "submit")
ACTOR_RELABEL = {"exec": "first_ping", "boot": "worker_main_boot"}

# Pipeline phases, innermost first: a slice where any stage computes is
# charged to compute; xfer / recv_wait only soak the inter-stage fetch
# time no compute covers (both are BLOCKING: the compute thread stalls
# inside them).  xfer_overlap is deliberately LAST — it elapses on a
# prefetch thread concurrently with compute, so any slice compute also
# covers is charged to compute and xfer_overlap keeps only its EXPOSED
# remainder; hidden transfer = its raw union length minus that share.
# The wrapping pp/step span is deliberately absent — it covers the
# whole step, so including it would relabel the bubble as driver time;
# instead whatever no inner pp span covers inside the fit window IS the
# bubble (schedule gaps + driver pump + stage stall).
PP_PHASE_PRIORITY = ("stage_fwd", "stage_bwd", "xfer", "recv_wait",
                     "apply", "ckpt", "recover", "xfer_overlap")
PP_RELABEL = {}

# Disaggregated-serving phases, innermost first: engine compute
# (spec_draft/spec_verify/prefill/decode) beats the KV movement spans
# (kv/export + kv/import inside the replicas, kv/handoff around the
# prefill hop on the driver), which beat the serve wrappers.  The
# wrapping serve/request span soaks only routing/queueing/dispatch time
# no inner phase explains.  After the sweep export/import/handoff merge
# into one "kv_xfer" bucket (they are disjoint by then) and admit
# reports as "route".
SERVE_PHASE_PRIORITY = ("spec_draft", "spec_verify", "prefill", "decode",
                        "export", "import", "handoff", "replica", "admit",
                        "request")
SERVE_RELABEL = {"admit": "route"}
SERVE_KV_XFER = ("export", "import", "handoff")


def _union(ivals):
    """Merge [(s, e), ...] into disjoint sorted intervals."""
    out = []
    for s, e in sorted(ivals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _subtract(ivals, covered):
    """Disjoint sorted `ivals` minus disjoint sorted `covered`."""
    out = []
    ci = 0
    for s, e in ivals:
        while ci < len(covered) and covered[ci][1] <= s:
            ci += 1
        j = ci
        cur = s
        while j < len(covered) and covered[j][0] < e:
            cs, ce = covered[j]
            if cs > cur:
                out.append((cur, min(cs, e)))
            cur = max(cur, ce)
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _len(ivals):
    return sum(e - s for s, e in ivals)


def attribute(spans_flat, t0, t1, priority=PHASE_PRIORITY):
    """Charge [t0, t1] to phases by priority; returns (per-phase seconds,
    unattributed seconds)."""
    by_kind = {}
    for rec in spans_flat:
        if rec["start"] is None or rec["end"] is None:
            continue
        s, e = max(rec["start"], t0), min(rec["end"], t1)
        if e > s:
            by_kind.setdefault(rec["kind"], []).append((s, e))
    covered = []
    phases = {}
    for kind in priority:
        ivals = _union(by_kind.get(kind, []))
        fresh = _subtract(ivals, covered)
        phases[kind] = _len(fresh)
        covered = _union(covered + fresh)
    wall = t1 - t0
    return phases, wall - _len(covered)


def _write(update: dict):
    """Merge `update` into SCALE_ATTRIB.json (modes accumulate, so the
    queued-task row survives an actor_storm run and vice versa)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SCALE_ATTRIB.json")
    doc = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError:
            doc = {}
    doc.update(update)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(f"wrote {path}")


def _report(ranked, total_s, unattributed, coverage):
    for k, v in ranked:
        print(f"  {k:16s} {v:8.3f}s  {v / total_s:6.1%}")
    print(f"  {'unattributed':16s} {unattributed:8.3f}s  "
          f"{unattributed / total_s:6.1%}   (coverage {coverage:.1%})")


def run_actor_storm(n: int = 200):
    """Attribute an actor storm's wall clock to spawn-path phases.

    The hostd's fork/boot spans carry no trace context (no task is
    active while a worker spawns), so instead of state.spans(tid) the
    whole cluster event stream for the storm window is scraped and
    paired; the union sweep then charges the window across fork, boot,
    first ping and the driver-side lease/dispatch phases."""
    ray_tpu.init(
        num_cpus=2, object_store_memory=256 << 20,
        _system_config={"events_ring_size": 1 << 18,
                        "max_workers_per_node": max(600, n + 50)})

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return os.getpid()

    with tracing.trace("actor_storm_attrib"):
        t0 = time.time()
        actors = [Pinger.remote() for _ in range(n)]
        ray_tpu.get([a.ping.remote() for a in actors])
        t1 = time.time()
    total_s = t1 - t0
    print(f"actor_storm(traced): {n} actors created+pinged in "
          f"{total_s:.2f}s ({n / total_s:.1f}/s)")
    time.sleep(1.5)                                     # let rings settle

    evs = state.events(since=t0 - 1.0)
    table, _roots = state.build_spans(evs)
    flat = list(table.values())
    phases, unattributed = attribute(flat, t0, t1,
                                     priority=ACTOR_PHASE_PRIORITY)
    phases = {ACTOR_RELABEL.get(k, k): v for k, v in phases.items()}
    coverage = 1.0 - unattributed / total_s
    ranked = sorted(((k, v) for k, v in phases.items() if v > 0),
                    key=lambda kv: -kv[1])
    doc = {
        "n": n,
        "wall_clock_s": round(total_s, 3),
        "create_rate_per_s": round(n / total_s, 1),
        "spans_observed": len(flat),
        "phases_s": {k: round(v, 3) for k, v in ranked},
        "phases_frac": {k: round(v / total_s, 4) for k, v in ranked},
        "top_phases": [k for k, _ in ranked[:3]],
        "unattributed_s": round(unattributed, 3),
        "coverage": round(coverage, 4),
    }
    _report(ranked, total_s, unattributed, coverage)
    _write({"actor_storm": doc})
    ray_tpu.shutdown()
    # Spawn-path phases MUST be visible — that is this mode's point.
    # Coverage is reported but not gated at 0.9: parked-lease park time
    # on the hostd side is intentionally unspanned.
    have = set(doc["phases_s"])
    missing = {"zygote_fork", "first_ping"} - have
    assert not missing, f"spawn-path phases absent from attribution: {missing}"


def _pp_stage_fwd(params, x):
    import numpy as np
    y = np.tanh(x @ params["w"] + params["b"])
    return y, (x, y)


def _pp_stage_bwd(params, cache, gy):
    import numpy as np
    x, y = cache
    gz = gy * (1.0 - y * y)
    return gz @ params["w"].T, {"w": x.T @ gz, "b": gz.sum(axis=0)}


def _pp_loss_fwd(y, t):
    d = y - t
    return float((d * d).mean()), (d, y.size)


def _pp_loss_bwd(cache):
    d, n = cache
    return 2.0 * d / n


def run_pipeline(steps: int = 6, stages: int = 4, n_micro: int = 8,
                 micro_batch: int = 64, width: int = 256,
                 interleave: int = 2, prefetch: bool = True):
    """Attribute an MPMD pipeline fit's wall clock to pp phases.

    Stage workers record pp/stage_fwd, pp/stage_bwd, pp/xfer (blocking)
    and pp/xfer_overlap + pp/recv_wait (the pre-push path) plus the
    update-boundary spans without a trace context, so (like actor_storm)
    the whole cluster event stream for the fit window is scraped and
    union-swept.  The leftover inside the window is the bubble the
    schedule could not fill (plus driver pump overhead not under any
    span), reported next to the metrics-side per-step bubble fraction.
    Transfer is split honestly: blocking xfer + recv_wait sit on the
    critical path; xfer_overlap's hidden share (raw elapsed minus its
    compute-uncovered remainder) is transfer the prefetch window
    actually took OFF the critical path, not just relabelled.
    """
    import numpy as np

    from ray_tpu.train import PipelineTrainer

    ray_tpu.init(
        num_cpus=stages + 2, object_store_memory=256 << 20,
        _system_config={"events_ring_size": 1 << 18})
    rng = np.random.default_rng(0)
    params = [{"w": rng.normal(0, 0.3, (width, width)),
               "b": np.zeros(width)} for _ in range(stages)]
    tr = PipelineTrainer(
        (_pp_stage_fwd, _pp_stage_bwd, _pp_loss_fwd, _pp_loss_bwd),
        params, lr=0.05, n_microbatches=n_micro, schedule="1f1b",
        interleave=interleave, prefetch=prefetch)

    def data(step):
        r = np.random.default_rng(100 + step)
        xs = [r.normal(size=(micro_batch, width)) for _ in range(n_micro)]
        ts = [np.zeros((micro_batch, width)) for _ in range(n_micro)]
        return xs, ts

    t0 = time.time()
    hist = tr.fit(data, steps)
    t1 = time.time()
    total_s = t1 - t0
    print(f"pp(fit): {steps} steps x {n_micro} microbatches over "
          f"{stages} MPMD stages in {total_s:.2f}s")
    time.sleep(1.5)                                     # let rings settle

    evs = state.events(since=t0 - 1.0)
    table, _roots = state.build_spans(evs)
    flat = [r for r in table.values() if r.get("plane") == "pp"]
    phases, unattributed = attribute(flat, t0, t1,
                                     priority=PP_PHASE_PRIORITY)
    phases = {PP_RELABEL.get(k, k): v for k, v in phases.items()}
    bubble = float(np.mean([h["bubble_fraction"] for h in hist]))
    coverage = 1.0 - unattributed / total_s
    # Hidden vs exposed transfer: xfer_overlap's raw union length is
    # the transfer time that ELAPSED on prefetch threads; the union
    # sweep charged compute first, so phases["xfer_overlap"] is only
    # the slice nothing computed under (still exposed).  The difference
    # is transfer genuinely hidden under compute.
    ov_raw = _len(_union([(max(r["start"], t0), min(r["end"], t1))
                          for r in flat
                          if r["kind"] == "xfer_overlap"
                          and r["start"] is not None
                          and r["end"] is not None
                          and min(r["end"], t1) > max(r["start"], t0)]))
    ov_exposed = phases.get("xfer_overlap", 0.0)
    xfer_blocking = phases.get("xfer", 0.0) + phases.get("recv_wait", 0.0)
    hidden = max(0.0, ov_raw - ov_exposed)
    hidden_frac = hidden / ov_raw if ov_raw > 0 else 0.0
    ranked = sorted(((k, v) for k, v in phases.items() if v > 0),
                    key=lambda kv: -kv[1])
    doc = {
        "workload": "pp_fit",
        "stages": stages,
        "n_micro": n_micro,
        "steps": steps,
        "interleave": interleave,
        "prefetch": prefetch,
        "wall_clock_s": round(total_s, 3),
        "spans_observed": len(flat),
        "phases_s": {k: round(v, 3) for k, v in ranked},
        "phases_frac": {k: round(v / total_s, 4) for k, v in ranked},
        "top_phases": [k for k, _ in ranked[:3]],
        "xfer_blocking_s": round(xfer_blocking, 3),
        "xfer_overlap_total_s": round(ov_raw, 3),
        "xfer_hidden_s": round(hidden, 3),
        "xfer_hidden_frac": round(hidden_frac, 4),
        "bubble_s": round(unattributed, 3),
        "bubble_frac_of_wall": round(1.0 - coverage, 4),
        "bubble_fraction_metric": round(bubble, 4),
        "coverage": round(coverage, 4),
    }
    _report(ranked, total_s, unattributed, coverage)
    print(f"  (unattributed here = pipeline bubble + driver pump)")
    print(f"  per-step bubble fraction (pp_bubble_fraction): {bubble:.1%}")
    print(f"  transfer: blocking {xfer_blocking:.3f}s on critical path; "
          f"{hidden:.3f}s of {ov_raw:.3f}s overlapped transfer hidden "
          f"under compute ({hidden_frac:.1%})")
    # Overlapped runs land on the canonical "pp" key; a blocking
    # (prefetch off) run lands beside it so the hidden-transfer claim
    # stays comparable against its own baseline.
    _write({"pp" if prefetch else "pp_blocking": doc})
    tr.shutdown()
    ray_tpu.shutdown()
    # The pipeline phases MUST be visible — that is this mode's point.
    have = set(doc["phases_s"])
    missing = {"stage_fwd", "stage_bwd"} - have
    assert not missing, f"pp phases absent from attribution: {missing}"


def run_serve(n_requests: int = 24, groups: int = 4,
              prefix_len: int = 48, budget: int = 12):
    """Attribute a disaggregated-serving workload's request wall across
    route / prefill / kv_xfer / decode phases.

    Runs 1 prefill + 1 decode replica (serve/kv_tier), issues
    `n_requests` token prompts in `groups` shared-prefix groups through
    DisaggLLMHandle.stream, then scrapes the cluster event stream for
    the window (replica engines record engine/kv spans without a trace
    context, like the pp stages) and union-sweeps it.  The driver-side
    kv/handoff span plus the replica-side kv/export + kv/import spans
    merge into one "kv_xfer" bucket after the sweep — by then they are
    disjoint, so the merge cannot double-count."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ray_tpu.init(
        num_cpus=4, object_store_memory=256 << 20,
        _system_config={"events_ring_size": 1 << 18})
    from ray_tpu import serve
    serve.start()
    handle = serve.run_disaggregated(
        model="gpt", config="nano", max_lanes=8, seed=0,
        name="llm_attrib")

    prompts = []
    for i in range(n_requests):
        g = i % groups
        shared = [1 + g * 7 + (t % 96) for t in range(prefix_len)]
        prompts.append(shared + [200 + i, 201 + i, 202 + i])
    list(handle.stream(prompts[0], 2))       # warm jit + routing tables

    with tracing.trace("serve_attrib"):
        t0 = time.time()
        for p in prompts:
            for _ in handle.stream(p, budget):
                pass
        t1 = time.time()
    total_s = t1 - t0
    print(f"serve(disagg): {n_requests} requests x {budget} tokens "
          f"({groups} shared-prefix groups) in {total_s:.2f}s")
    time.sleep(1.5)                                     # let rings settle

    evs = state.events(since=t0 - 1.0)
    table, _roots = state.build_spans(evs)
    flat = [r for r in table.values()
            if r.get("plane") in ("serve", "engine", "kv")]
    phases, unattributed = attribute(flat, t0, t1,
                                     priority=SERVE_PHASE_PRIORITY)
    kv_xfer = sum(phases.pop(k, 0.0) for k in SERVE_KV_XFER)
    phases["kv_xfer"] = kv_xfer
    phases = {SERVE_RELABEL.get(k, k): v for k, v in phases.items()}
    coverage = 1.0 - unattributed / total_s
    ranked = sorted(((k, v) for k, v in phases.items() if v > 0),
                    key=lambda kv: -kv[1])
    doc = {
        "workload": "serve_disagg",
        "n_requests": n_requests,
        "groups": groups,
        "budget": budget,
        "wall_clock_s": round(total_s, 3),
        "spans_observed": len(flat),
        "phases_s": {k: round(v, 3) for k, v in ranked},
        "phases_frac": {k: round(v / total_s, 4) for k, v in ranked},
        "top_phases": [k for k, _ in ranked[:3]],
        "kv_xfer_s": round(kv_xfer, 3),
        "unattributed_s": round(unattributed, 3),
        "coverage": round(coverage, 4),
    }
    _report(ranked, total_s, unattributed, coverage)
    _write({"serve": doc})
    serve.shutdown()
    ray_tpu.shutdown()
    # The disagg phases MUST be visible — that is this mode's point.
    have = set(doc["phases_s"])
    missing = {"prefill", "decode", "kv_xfer"} - have
    assert not missing, f"serve phases absent from attribution: {missing}"


# Actor/learner RL phases, innermost first on the DRIVER's critical
# path: learn (the jitted V-trace step) and publish (put + fan-out)
# happen on the driver thread, adopt on the rollout actors, and rollout
# spans elapse on the actors CONCURRENTLY with everything — so rollout
# is last and keeps only its exposed remainder (driver wall spent
# purely waiting on sample delivery), while its raw union length is
# reported separately as the gang's total rollout wall.
RL_PHASE_PRIORITY = ("learn", "publish", "adopt", "rollout")


def run_rl(min_updates: int = 30):
    """Attribute an async actor/learner RL loop's wall clock across
    rollout / publish / adopt / learn.

    Runs the Podracer controller (2 CartPole rollout actors feeding the
    stale-tolerant V-trace learner, publish every update) for
    `min_updates` learner updates, then scrapes the cluster event
    stream for the window (rollout/adopt spans live in the actor rings,
    publish/learn in the driver's) and union-sweeps the `rl` plane.
    The headline ratio is publish wall vs the gang's rollout wall — the
    in-place publication path is supposed to be invisible next to
    generation."""
    ray_tpu.init(
        num_cpus=4, object_store_memory=256 << 20,
        _system_config={"events_ring_size": 1 << 18})
    from ray_tpu.rl import PodracerConfig
    cfg = (PodracerConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                     rollout_fragment_length=32)
           .training(staleness_bound=2, publish_interval=1,
                     min_updates_per_step=1)
           .debugging(seed=0))
    algo = cfg.build()
    algo.train()                                  # warm jit + gang
    t0 = time.time()
    while algo.learner.num_updates < min_updates + 1:
        algo.train()
    t1 = time.time()
    total_s = t1 - t0
    updates = algo.learner.num_updates - 1
    print(f"rl(podracer): {updates} learner updates / "
          f"{algo.learner.version} published versions in {total_s:.2f}s")
    time.sleep(1.5)                                     # let rings settle

    evs = state.events(since=t0 - 1.0)
    table, _roots = state.build_spans(evs)
    flat = [r for r in table.values() if r.get("plane") == "rl"]
    phases, unattributed = attribute(flat, t0, t1,
                                     priority=RL_PHASE_PRIORITY)
    coverage = 1.0 - unattributed / total_s

    def raw(kind):
        return _len(_union([(max(r["start"], t0), min(r["end"], t1))
                            for r in flat
                            if r["kind"] == kind
                            and r["start"] is not None
                            and r["end"] is not None
                            and min(r["end"], t1) > max(r["start"], t0)]))

    rollout_raw = raw("rollout")
    publish_raw = raw("publish") + raw("adopt")
    ratio = publish_raw / rollout_raw if rollout_raw > 0 else 0.0
    ranked = sorted(((k, v) for k, v in phases.items() if v > 0),
                    key=lambda kv: -kv[1])
    doc = {
        "workload": "rl_podracer",
        "updates": updates,
        "published_versions": algo.learner.version,
        "wall_clock_s": round(total_s, 3),
        "spans_observed": len(flat),
        "phases_s": {k: round(v, 3) for k, v in ranked},
        "phases_frac": {k: round(v / total_s, 4) for k, v in ranked},
        "top_phases": [k for k, _ in ranked[:3]],
        "rollout_wall_s": round(rollout_raw, 3),
        "publish_wall_s": round(publish_raw, 3),
        "publish_frac_of_rollout": round(ratio, 4),
        "queue": algo.queue.stats(),
        "unattributed_s": round(unattributed, 3),
        "coverage": round(coverage, 4),
    }
    _report(ranked, total_s, unattributed, coverage)
    print(f"  rollout wall (gang total) {rollout_raw:.3f}s; publish+adopt "
          f"{publish_raw:.3f}s ({ratio:.1%} of rollout)")
    _write({"rl": doc})
    algo.stop()
    ray_tpu.shutdown()
    # The actor/learner phases MUST be visible — that is this mode's
    # point — and publication must stay small next to generation.
    have = set(doc["phases_s"])
    missing = {"rollout", "learn", "publish"} - have
    assert not missing, f"rl phases absent from attribution: {missing}"


def main():
    ray_tpu.init(
        num_cpus=2, object_store_memory=256 << 20,
        _system_config={"events_ring_size": 1 << 18})

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(2000)])   # warm pool
    time.sleep(1.0)

    with tracing.trace("scale_attrib") as tid:
        t0 = time.time()
        refs = [nop.remote() for _ in range(N_TASKS)]
        submit_s = time.time() - t0
        ray_tpu.get(refs)
        t1 = time.time()
    total_s = t1 - t0
    print(f"queued_tasks(traced): {N_TASKS} submitted in {submit_s:.2f}s, "
          f"drained in {total_s:.2f}s")
    time.sleep(1.0)                                     # let rings settle

    tree = state.spans(tid)
    phases, unattributed = attribute(tree["spans"], t0, t1)
    coverage = 1.0 - unattributed / total_s
    ranked = sorted(((k, v) for k, v in phases.items() if v > 0),
                    key=lambda kv: -kv[1])
    doc = {
        "workload": "queued_tasks",
        "n": N_TASKS,
        "wall_clock_s": round(total_s, 3),
        "submit_s": round(submit_s, 3),
        "spans_observed": len(tree["spans"]),
        "torn_spans": tree["torn"],
        "phases_s": {k: round(v, 3) for k, v in ranked},
        "phases_frac": {k: round(v / total_s, 4) for k, v in ranked},
        "top_phases": [k for k, _ in ranked[:2]],
        "unattributed_s": round(unattributed, 3),
        "coverage": round(coverage, 4),
    }
    _report(ranked, total_s, unattributed, coverage)
    _write(doc)
    ray_tpu.shutdown()
    assert coverage >= 0.9, f"attribution coverage {coverage:.1%} < 90%"


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "actor_storm":
        run_actor_storm(int(sys.argv[2]) if len(sys.argv) > 2 else 200)
    elif len(sys.argv) > 1 and sys.argv[1] == "serve":
        run_serve(int(sys.argv[2]) if len(sys.argv) > 2 else 24)
    elif len(sys.argv) > 1 and sys.argv[1] == "rl":
        run_rl(int(sys.argv[2]) if len(sys.argv) > 2 else 30)
    elif len(sys.argv) > 1 and sys.argv[1] == "pp":
        # pp [steps] [interleave] [prefetch:0|1]
        run_pipeline(
            int(sys.argv[2]) if len(sys.argv) > 2 else 6,
            interleave=int(sys.argv[3]) if len(sys.argv) > 3 else 2,
            prefetch=bool(int(sys.argv[4])) if len(sys.argv) > 4 else True)
    else:
        main()
