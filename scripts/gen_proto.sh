#!/bin/sh
# Regenerate ray_tpu/protocol/raytpu_pb2.py from raytpu.proto.
# The generated file is checked in (no protoc needed at runtime).
set -e
cd "$(dirname "$0")/.."
protoc --python_out=ray_tpu/protocol --proto_path=ray_tpu/protocol \
    ray_tpu/protocol/raytpu.proto
echo "generated ray_tpu/protocol/raytpu_pb2.py"
