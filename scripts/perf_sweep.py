"""Perf experiment matrix for the GPT-2 train step on the real chip.

Usage: python scripts/perf_sweep.py [exp ...]
Each experiment prints steady-state tokens/s.  Run sequentially (one chip).
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")
from ray_tpu.models import gpt  # noqa: E402


def time_step(step, state, tokens, n=10, scan_steps=None):
    # Warmup/compile.
    for _ in range(2):
        state, metrics = step(state, {"tokens": tokens})
    jax.block_until_ready(metrics["loss"])
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(n):
        state, metrics = step(state, {"tokens": tokens})
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    eff_steps = n * (scan_steps or 1)
    return tokens.size * eff_steps / dt, dt / eff_steps


def base(cfg_name="gpt2-small", batch=8, seq=1024, **cfg_over):
    cfg = gpt.CONFIGS[cfg_name]
    if cfg_over:
        cfg = gpt.GPTConfig(**{**cfg.__dict__, **cfg_over})
    init_state, train_step = gpt.make_train_step(cfg, optax.adamw(1e-4))
    state = init_state(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                cfg.vocab_size)
    return cfg, state, tokens, train_step


def exp_baseline():
    cfg, state, tokens, train_step = base()
    step = jax.jit(train_step, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"baseline b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_batch(b):
    cfg, state, tokens, train_step = base(batch=b)
    step = jax.jit(train_step, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"batch{b}: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_scan10():
    """10 steps inside one jit via lax.scan — measures dispatch overhead."""
    cfg, state, tokens, train_step = base()

    def multi(state, batch):
        def body(s, _):
            s, m = train_step(s, batch)
            return s, m["loss"]
        state, losses = jax.lax.scan(body, state, None, length=10)
        return state, {"loss": losses[-1]}

    step = jax.jit(multi, donate_argnums=0)
    tps, ms = time_step(step, state, tokens, n=3, scan_steps=10)
    print(f"scan10 b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_ref_attention():
    """XLA reference attention instead of the Pallas kernel."""
    import ray_tpu.ops.attention as att
    orig = att.flash_attention
    att.flash_attention = lambda q, k, v, **kw: att.reference_attention(
        q, k, v, causal=kw.get("causal", True))
    try:
        cfg, state, tokens, train_step = base()
        step = jax.jit(train_step, donate_argnums=0)
        tps, ms = time_step(step, state, tokens)
        print(f"ref-attn b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")
    finally:
        att.flash_attention = orig


def exp_fwd_only():
    cfg, state, tokens, _ = base()

    def fwd(state, batch):
        loss = gpt.loss_fn(state["params"], batch, cfg)
        return state, {"loss": loss}

    step = jax.jit(fwd, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"fwd-only b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_no_adamw():
    """SGD instead of adamw — isolates optimizer cost."""
    cfg = gpt.CONFIGS["gpt2-small"]
    init_state, train_step = gpt.make_train_step(cfg, optax.sgd(1e-4))
    state = init_state(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 1024), 0,
                                cfg.vocab_size)
    step = jax.jit(train_step, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"sgd b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


EXPS = {
    "baseline": exp_baseline,
    "batch16": lambda: exp_batch(16),
    "batch32": lambda: exp_batch(32),
    "scan10": exp_scan10,
    "refattn": exp_ref_attention,
    "fwdonly": exp_fwd_only,
    "sgd": exp_no_adamw,
}



def exp_nohead():
    """Loss without the vocab projection + softmax — isolates head cost."""
    import jax
    cfg, state, tokens, _ = base()

    def loss_nohead(params, batch):
        logits_in, _ = _forward_trunk(params, batch["tokens"], cfg)
        return jnp.mean(jnp.square(logits_in.astype(jnp.float32)))

    def _forward_trunk(params, toks, c):
        from ray_tpu.models.gpt import _block, _layernorm
        from functools import partial
        x = params["tok_embed"][toks].astype(c.dtype)
        x = x + params["pos_embed"][: toks.shape[1]][None].astype(c.dtype)
        block = partial(_block, config=c, mesh=None)
        def body(xx, lp):
            xx, aux = block(xx, lp)
            return xx, aux
        x, auxes = jax.lax.scan(body, x, params["blocks"])
        x = _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
        return x, auxes

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_nohead)(state["params"], batch)
        return state, {"loss": loss}

    stepj = jax.jit(step, donate_argnums=0)
    tps, ms = time_step(stepj, state, tokens)
    print(f"nohead b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_bf16params():
    """Whole param tree in bf16 (halves weight HBM traffic, no per-layer casts)."""
    cfg, state, tokens, train_step = base()
    state["params"] = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32 else x, state["params"])
    import optax
    opt = optax.adamw(1e-4)
    state["opt_state"] = opt.init(state["params"])
    init_state, train_step = gpt.make_train_step(cfg, opt)
    step = jax.jit(train_step, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"bf16params b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_unroll():
    """lax.scan over layers with unroll= full depth."""
    import optax
    from functools import partial
    cfg, state, tokens, _ = base()
    from ray_tpu.models.gpt import _block, _layernorm
    import ray_tpu.models.gpt as G
    orig_scan = jax.lax.scan
    def scan_unrolled(f, init, xs, **kw):
        kw.pop("unroll", None)
        return orig_scan(f, init, xs, unroll=True, **kw)
    jax.lax.scan = scan_unrolled
    try:
        init_state, train_step = gpt.make_train_step(cfg, optax.adamw(1e-4))
        step = jax.jit(train_step, donate_argnums=0)
        tps, ms = time_step(step, state, tokens)
        print(f"unroll b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")
    finally:
        jax.lax.scan = orig_scan


EXPS["nohead"] = exp_nohead
EXPS["bf16params"] = exp_bf16params
EXPS["unroll"] = exp_unroll

def exp_untied():
    """tie_embeddings=False: isolates the tied-head transpose + grad-add."""
    cfg, state, tokens, train_step = base(tie_embeddings=False)
    step = jax.jit(train_step, donate_argnums=0)
    tps, ms = time_step(step, state, tokens)
    print(f"untied b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


EXPS["untied"] = exp_untied

def exp_gradonly():
    """value_and_grad of the full loss, no optimizer apply."""
    import jax
    cfg, state, tokens, _ = base()

    def step(state, batch):
        loss, grads = jax.value_and_grad(gpt.loss_fn)(
            state["params"], batch, cfg)
        return state, {"loss": loss, "g": grads["final_ln_scale"][0]}

    stepj = jax.jit(step, donate_argnums=0)
    tps, ms = time_step(stepj, state, tokens)
    print(f"gradonly b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


def exp_fwdloss():
    """forward + fused loss only (no grad)."""
    import jax
    cfg, state, tokens, _ = base()

    def step(state, batch):
        loss = gpt.loss_fn(state["params"], batch, cfg)
        return state, {"loss": loss}

    stepj = jax.jit(step, donate_argnums=0)
    tps, ms = time_step(stepj, state, tokens)
    print(f"fwdloss b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


EXPS["gradonly"] = exp_gradonly
EXPS["fwdloss"] = exp_fwdloss

def exp_noattn():
    """Attention replaced by identity: isolates attention fwd+bwd cost."""
    import ray_tpu.ops.attention as att
    import ray_tpu.models.gpt as G
    orig = G.flash_attention
    G.flash_attention = lambda q, k, v, **kw: q
    try:
        cfg, state, tokens, train_step = base()
        step = jax.jit(train_step, donate_argnums=0)
        tps, ms = time_step(step, state, tokens)
        print(f"noattn b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")
    finally:
        G.flash_attention = orig


EXPS["noattn"] = exp_noattn

def exp_fwdtrunk():
    """Trunk forward only (no head): embed + 12 blocks + final LN."""
    import jax
    cfg, state, tokens, _ = base()

    def step(state, batch):
        x, aux = gpt.forward_trunk(state["params"], batch["tokens"], cfg)
        return state, {"loss": jnp.mean(jnp.square(x.astype(jnp.float32)))}

    stepj = jax.jit(step, donate_argnums=0)
    tps, ms = time_step(stepj, state, tokens)
    print(f"fwdtrunk b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")


EXPS["fwdtrunk"] = exp_fwdtrunk

def exp_fusedqkv():
    """QKV as ONE [d, 3*d] matmul instead of three einsums."""
    import jax
    import ray_tpu.models.gpt as G
    orig_block = G._block

    def fused_block(x, p, config, mesh):
        c = config
        h = G._layernorm(x, p["ln1_scale"], p["ln1_bias"])
        wqkv = jnp.concatenate(
            [p["wq"].reshape(c.d_model, -1),
             p["wk"].reshape(c.d_model, -1),
             p["wv"].reshape(c.d_model, -1)], axis=-1).astype(h.dtype)
        qkv = jnp.einsum("bld,de->ble", h, wqkv)
        d3 = c.n_heads * c.head_dim
        q = qkv[..., :d3].reshape(*qkv.shape[:2], c.n_heads, c.head_dim)
        k = qkv[..., d3:2*d3].reshape(*qkv.shape[:2], c.n_heads, c.head_dim)
        v = qkv[..., 2*d3:].reshape(*qkv.shape[:2], c.n_heads, c.head_dim)
        attn = G.flash_attention(q, k, v, causal=True)
        attn_out = jnp.einsum("blhk,hkd->bld", attn,
                              p["wo"].astype(h.dtype))
        x = x + attn_out
        h2 = G._layernorm(x, p["ln2_scale"], p["ln2_bias"])
        hidden = jax.nn.gelu(
            jnp.einsum("bld,df->blf", h2, p["w_up"].astype(h2.dtype)))
        mlp_out = jnp.einsum("blf,fd->bld", hidden,
                             p["w_down"].astype(h2.dtype))
        x = x + mlp_out
        return x, jnp.zeros((), jnp.float32)

    G._block = fused_block
    try:
        cfg, state, tokens, train_step = base()
        step = jax.jit(train_step, donate_argnums=0)
        tps, ms = time_step(step, state, tokens)
        print(f"fusedqkv b8: {tps:,.0f} tok/s  {ms*1e3:.1f} ms/step")
    finally:
        G._block = orig_block


EXPS["fusedqkv"] = exp_fusedqkv

def exp_batch24():
    exp_batch(24)


EXPS["batch24"] = exp_batch24


def exp_batch32():
    exp_batch(32)


def exp_batch48():
    exp_batch(48)


EXPS["batch32"] = exp_batch32
EXPS["batch48"] = exp_batch48



if __name__ == "__main__":
    names = sys.argv[1:] or list(EXPS)
    for name in names:
        EXPS[name]()
