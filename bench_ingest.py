"""Input-pipeline benchmark: sync iter_batches vs the overlapped device feed.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Runs the same input-bound training loop (jitted matmul step over a
materialized dataset's float32 feature column) two ways:

  * sync baseline — `iter_batches()` fetches + assembles on the training
    thread, then a blocking `jax.device_put` stages the batch, then the
    step runs: fetch latency, assembly, H2D, and compute all serialize;
  * overlapped — `iter_device_batches()`: a background thread fetches and
    assembles into a bounded queue while double-buffered H2D staging keeps
    the next batch in flight during the current step, so the step loop
    only ever waits when the producer is genuinely behind.

Block-fetch latency is EMULATED (`--fetch-latency-ms`, default 40): on
this single-node bench host every block is already sealed in the local
shm store, whereas the production trainer pulls shard blocks from peer
hosts' stores (or storage) with a real per-block RTT.  The emulation adds
that RTT in `_block_iter` — the same hook both the sync and overlapped
paths consume — so the two modes pay identical ingest cost and differ
only in WHERE it is paid (training thread vs background producer).  Both
paths run the same assembly/H2D code on the same blocks; the exactness
gate checks the overlapped feed is numerically identical to the sync
path before anything is timed.

Reports overlapped steps/s; `vs_baseline` is the ratio over sync.  The
device-idle fraction per mode (time the step loop spent waiting on data:
measured ingest+H2D time for sync, the producer-starved wait for
overlapped) shows the mechanism.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--blocks", type=int, default=16)
    ap.add_argument("--dim", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--step-iters", type=int, default=4,
                    help="matmul iterations per jitted step (compute knob)")
    ap.add_argument("--fetch-latency-ms", type=float, default=40.0,
                    help="emulated per-block fetch RTT (cross-host object "
                         "transfer on a real cluster; 0 disables)")
    ap.add_argument("--rounds", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu
    from ray_tpu import data as rd
    from ray_tpu.data import DataIterator

    fetch_s = args.fetch_latency_ms / 1000.0

    class EmulatedFetchIterator(DataIterator):
        """Adds the emulated cross-host fetch RTT per block, in the
        `_block_iter` hook shared by iter_batches AND iter_device_batches
        — both modes pay it; only the paying thread differs."""

        def _block_iter(self, prefetch: int = 4):
            for b in super()._block_iter(prefetch):
                if fetch_s:
                    time.sleep(fetch_s)
                yield b

    ray_tpu.init(num_cpus=4, object_store_memory=256 << 20)
    try:
        dim, bs, iters = args.dim, args.batch_size, args.step_iters

        def add_x(b):
            ids = b["id"].astype(np.float32)
            b["x"] = np.repeat(ids[:, None], dim, axis=1) * 1e-3
            return b

        ds = (rd.range(args.rows, parallelism=args.blocks)
              .map_batches(add_x).materialize())
        it = EmulatedFetchIterator(ds.streaming_split(1)[0]._refs)

        @jax.jit
        def step(w, x):
            y = x
            for _ in range(iters):
                y = jnp.tanh(y @ w)
            return y.sum()

        w = jax.random.normal(jax.random.PRNGKey(0), (dim, dim),
                              jnp.float32) * 0.05
        step(w, jnp.zeros((bs, dim), jnp.float32)).block_until_ready()

        # -- exactness gate -------------------------------------------------
        sync_ref = [b["x"].copy()
                    for b in it.iter_batches(batch_size=bs, drop_last=True)]
        dev_feed = it.iter_device_batches(batch_size=bs, drop_last=True)
        dev_ref = [np.asarray(b["x"]) for b in dev_feed]
        assert len(sync_ref) == len(dev_ref) > 0
        for a, b in zip(sync_ref, dev_ref):
            np.testing.assert_array_equal(a, b)
        del sync_ref, dev_ref
        n_steps = args.rows // bs

        # -- sync baseline --------------------------------------------------
        def run_sync():
            ingest_s = 0.0
            t0 = time.perf_counter()
            gen = iter(it.iter_batches(batch_size=bs, drop_last=True))
            steps = 0
            while True:
                ti = time.perf_counter()
                batch = next(gen, None)
                if batch is None:
                    break
                x = jax.device_put(batch["x"])
                x.block_until_ready()
                ingest_s += time.perf_counter() - ti
                step(w, x).block_until_ready()
                steps += 1
            wall = time.perf_counter() - t0
            assert steps == n_steps
            return wall, ingest_s / wall

        # -- overlapped device feed -----------------------------------------
        def run_overlapped():
            t0 = time.perf_counter()
            feed = it.iter_device_batches(batch_size=bs, drop_last=True)
            steps = 0
            for batch in feed:
                step(w, batch["x"]).block_until_ready()
                steps += 1
            wall = time.perf_counter() - t0
            assert steps == n_steps
            stats = feed.stats()
            return wall, stats["consumer_wait_s"] / wall

        run_sync()          # warm both paths once before timing
        run_overlapped()
        sync_runs = [run_sync() for _ in range(args.rounds)]
        over_runs = [run_overlapped() for _ in range(args.rounds)]

        sync_wall = statistics.median(r[0] for r in sync_runs)
        over_wall = statistics.median(r[0] for r in over_runs)
        sync_sps = n_steps / sync_wall
        over_sps = n_steps / over_wall
        print(json.dumps({
            "metric": "ingest_overlapped_steps_s",
            "value": round(over_sps, 2),
            "unit": "steps_per_s",
            "vs_baseline": round(over_sps / sync_sps, 3),
            "steps_s_sync": round(sync_sps, 2),
            "device_idle_frac_sync":
                round(statistics.median(r[1] for r in sync_runs), 3),
            "device_idle_frac_overlapped":
                round(statistics.median(r[1] for r in over_runs), 3),
            "exactness_gate": "passed",
            "steps_per_epoch": n_steps,
            "batch_mib": round(bs * dim * 4 / (1 << 20), 2),
            "fetch_latency_ms": args.fetch_latency_ms,
            "rounds": args.rounds,
        }))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
