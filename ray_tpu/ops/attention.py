"""Attention ops: blockwise (flash) attention with a Pallas TPU kernel.

No reference counterpart — Ray delegates compute to hosted frameworks
(SURVEY.md §5 "Long-context: absent").  Here attention is a core op: the
Pallas kernel keeps the softmax accumulation in VMEM (online softmax, never
materialising the [L, L] score matrix in HBM) and tiles the contraction onto
the MXU; a pure-jnp fallback covers CPU tests and odd shapes.

Layouts: q/k/v are [batch, length, heads, head_dim] (BLHD) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        segment_ids=None) -> jax.Array:
    """Plain XLA attention (fallback + ground truth for kernel tests)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = _build_mask(q.shape[1], k.shape[1], causal, segment_ids)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _build_mask(q_len, k_len, causal, segment_ids):
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), bool),
                        k=k_len - q_len)[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask & seg)
    return mask


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_k: int, causal: bool, scale: float,
                  n_kv_blocks: int):
    """One (batch*head, q_block, kv_block) grid step: online softmax.

    K/V arrive one VMEM block per grid step (the grid's last dim streams
    them from HBM — memory is O(block), not O(kv_len)); softmax state
    persists in VMEM scratch across the kv sweep for a given q block.
    Refs: q [bq, d], k/v [block_k, d], o [bq, d]; scratch m/l [bq, 1] f32,
    acc [bq, d] f32.
    """
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    bq = q_ref.shape[0]
    q_offset = q_idx * bq
    kv_offset = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        s = q @ k_blk.T                                        # [bq, block_k]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + p @ v_blk

    if causal:
        # KV blocks strictly above the diagonal contribute nothing.
        pl.when(q_offset + bq - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv_blocks - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...]
                      / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


try:  # Pallas import kept lazy-safe for platforms without it.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: Optional[bool] = None):
    """Blockwise attention via Pallas.  Falls back to XLA attention when the
    shape does not tile (length % block != 0) or Pallas is unavailable.

    Differentiable: Pallas forward + custom VJP whose backward recomputes
    attention with the XLA path (flash-style Pallas backward kernel is a
    planned optimisation; the recompute keeps forward memory O(block) and
    correctness exact)."""
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                               interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                              interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention(q, k, v, causal=causal,
                                            scale=scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _flash_forward_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_k = min(block_k, kv_len)
    if (not _HAS_PALLAS or q_len % block_q or kv_len % block_k
            or d not in (64, 128, 256) or (causal and q_len != kv_len)):
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n_kv_blocks = kv_len // block_k

    # Fold batch and heads into the grid; kernel sees [len, d] slices.
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, q_len, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, kv_len, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, kv_len, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               scale=scale, n_kv_blocks=n_kv_blocks)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, q_len // block_q, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, q_len, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, q_len, d).transpose(0, 2, 1, 3)
