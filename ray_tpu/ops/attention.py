"""Attention ops: blockwise (flash) attention with a Pallas TPU kernel.

No reference counterpart — Ray delegates compute to hosted frameworks
(SURVEY.md §5 "Long-context: absent").  Here attention is a core op: the
Pallas kernel keeps the softmax accumulation in VMEM (online softmax, never
materialising the [L, L] score matrix in HBM) and tiles the contraction onto
the MXU; a pure-jnp fallback covers CPU tests and odd shapes.

Layouts: q/k/v are [batch, length, heads, head_dim] (BLHD) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        segment_ids=None) -> jax.Array:
    """Plain XLA attention (fallback + ground truth for kernel tests)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = _build_mask(q.shape[1], k.shape[1], causal, segment_ids)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _build_mask(q_len, k_len, causal, segment_ids):
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), bool),
                        k=k_len - q_len)[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask & seg)
    return mask


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                  acc_ref, *, block_k: int, causal: bool, scale: float,
                  n_kv_blocks: int):
    """One (batch*head, q_block, kv_block) grid step: online softmax.

    K/V arrive one VMEM block per grid step (the grid's last dim streams
    them from HBM — memory is O(block), not O(kv_len)); softmax state
    persists in VMEM scratch across the kv sweep for a given q block.
    Refs: q [bq, d], k/v [block_k, d], o [bq, d], lse [bq, 1] (saved for
    the backward); scratch m/l [bq, 1] f32, acc [bq, d] f32.
    """
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    bq = q_ref.shape[0]
    q_offset = q_idx * bq
    kv_offset = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        s = q @ k_blk.T                                        # [bq, block_k]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + p @ v_blk

    if causal:
        # KV blocks strictly above the diagonal contribute nothing.
        pl.when(q_offset + bq - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l_safe)


try:  # Pallas import kept lazy-safe for platforms without it.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 1024,
                    block_k: int = 1024, interpret: Optional[bool] = None):
    """Blockwise attention via Pallas.  Falls back to XLA attention when the
    shape does not tile (length % block != 0) or Pallas is unavailable.

    Differentiable end-to-end in Pallas: the forward saves (O, logsumexp)
    and the backward runs flash-style dq and dk/dv kernels (causal block
    skipping, f32 VMEM accumulators) — never materializing [L, L]."""
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                                 interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if lse is None:  # forward took the XLA fallback: recompute via XLA
        _, vjp = jax.vjp(
            lambda q, k, v: reference_attention(q, k, v, causal=causal,
                                                scale=scale), q, k, v)
        return vjp(g)
    return _flash_backward_impl(q, k, v, out, lse, g, causal, scale,
                                block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _use_pallas(q_len, kv_len, d, block_q, block_k, causal):
    return (_HAS_PALLAS and q_len % block_q == 0 and kv_len % block_k == 0
            and d in (64, 128, 256) and not (causal and q_len != kv_len))


def _fold_heads(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _fit_blocks(q_len, kv_len, block_q, block_k):
    """Clamp blocks to the lengths, then halve until they tile — lengths
    like 1536 must ride the Pallas path with 512-blocks rather than fall
    back to the [L,L]-materializing XLA reference."""
    block_q = min(block_q, q_len)
    block_k = min(block_k, kv_len)
    while block_q > 128 and q_len % block_q:
        block_q //= 2
    while block_k > 128 and kv_len % block_k:
        block_k //= 2
    return block_q, block_k


def _flash_forward_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _fit_blocks(q_len, kv_len, block_q, block_k)
    if not _use_pallas(q_len, kv_len, d, block_q, block_k, causal):
        return reference_attention(q, k, v, causal=causal, scale=scale), None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n_kv_blocks = kv_len // block_k

    # Fold batch and heads into the grid; kernel sees [len, d] slices.
    qr, kr, vr = _fold_heads(q), _fold_heads(k), _fold_heads(v)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               scale=scale, n_kv_blocks=n_kv_blocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, q_len // block_q, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, q_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, q_len, d).transpose(0, 2, 1, 3), lse


# ---------------------------------------------------------------------------
# Paged-KV attention (decode path for the inference engine)
# ---------------------------------------------------------------------------
#
# The KV cache lives in a preallocated block pool [num_blocks, block_size,
# kv_heads, head_dim]; each sequence owns a row of a block table mapping its
# logical context positions onto pool blocks (inference/kv_cache.py).  The
# decode step asks: one query per lane attends over that lane's block table.
# The Pallas kernel streams KV blocks from the pool via scalar-prefetched
# block-table indices (positions past the context length are masked, so
# unused table entries may point anywhere valid); the dense fallback gathers
# the table into a contiguous context and masks — it covers CPU tests, odd
# head dims, and the multi-token prefill path.


def paged_kv_update(k_pool, v_pool, k_new, v_new, block_tables, positions,
                    valid):
    """Scatter new K/V for one layer into the paged pools.

    k_pool/v_pool [NB, BS, KH, D]; k_new/v_new [B, T, KH, D];
    block_tables [B, MB] int32; positions [B, T] absolute positions;
    valid [B, T] bool — invalid slots (padding lanes, prompt overhang)
    are dropped instead of written (out-of-range index + mode="drop").
    """
    nb, bs, kh, d = k_pool.shape
    b, t = positions.shape
    blk = positions // bs
    blk = jnp.clip(blk, 0, block_tables.shape[1] - 1)
    phys = jnp.take_along_axis(block_tables, blk, axis=1)        # [B, T]
    flat = phys * bs + positions % bs                            # [B, T]
    flat = jnp.where(valid, flat, nb * bs)                       # OOB => drop
    flat = flat.reshape(-1)
    k_pool = k_pool.reshape(nb * bs, kh, d).at[flat].set(
        k_new.reshape(-1, kh, d), mode="drop").reshape(nb, bs, kh, d)
    v_pool = v_pool.reshape(nb * bs, kh, d).at[flat].set(
        v_new.reshape(-1, kh, d), mode="drop").reshape(nb, bs, kh, d)
    return k_pool, v_pool


def paged_attention_reference(q, k_pool, v_pool, block_tables, ctx_lens,
                              q_positions, *, scale=None):
    """Masked-dense paged attention (fallback + prefill path).

    q [B, T, H, D] at absolute q_positions [B, T]; pools [NB, BS, KH, D]
    (KH may divide H — GQA); ctx_lens [B] = tokens written per lane.
    Each query attends to context positions <= its own (the query's K/V
    must already be in the pool).  All-masked rows (inactive lanes) come
    out as a uniform average, never NaN (finite NEG_INF).
    """
    b, t, h, d = q.shape
    nb, bs, kh, _ = k_pool.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    max_ctx = block_tables.shape[1] * bs
    k_ctx = k_pool[block_tables].reshape(b, max_ctx, kh, d)
    v_ctx = v_pool[block_tables].reshape(b, max_ctx, kh, d)
    if h != kh:
        k_ctx = jnp.repeat(k_ctx, h // kh, axis=2)
        v_ctx = jnp.repeat(v_ctx, h // kh, axis=2)
    logits = jnp.einsum("bthd,bkhd->bhtk", q.astype(jnp.float32),
                        k_ctx.astype(jnp.float32)) * scale
    kpos = jnp.arange(max_ctx)
    mask = ((kpos[None, None, None, :] <= q_positions[:, None, :, None])
            & (kpos[None, None, None, :] < ctx_lens[:, None, None, None]))
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhtk,bkhd->bthd", probs, v_ctx.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, block_size: int,
                         q_per_kv: int, scale: float, n_blocks: int):
    """One (lane, kv_block) grid step of single-query paged attention.

    Scalar-prefetched block tables route each grid step's K/V DMA to the
    lane's physical block (see the in_specs index maps); this kernel only
    sees q [H, D], k/v [BS, KH, D] already in VMEM.  Online softmax
    state persists in scratch across the lane's kv sweep, exactly like
    the flash kernel above; blocks at/past the context length are
    skipped entirely (their DMA still lands, but compute is gated)."""
    lane = pl.program_id(0)
    blk = pl.program_id(1)
    base = blk * block_size

    @pl.when(blk == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(base < len_ref[lane])
    def _compute():
        h, d = q_ref.shape
        kh = h // q_per_kv
        q = q_ref[...].astype(jnp.float32) * scale           # [H, D]
        k_blk = k_ref[...].astype(jnp.float32)               # [BS, KH, D]
        v_blk = v_ref[...].astype(jnp.float32)
        q3 = q.reshape(kh, q_per_kv, d)
        # Batched over kv heads: [KH, QPK, D] x [BS, KH, D] -> [KH, QPK, BS]
        s = jax.lax.dot_general(
            q3, k_blk, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        s = s.reshape(h, block_size)
        pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < len_ref[lane], s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(kh, q_per_kv, block_size), v_blk,
            (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)              # [KH, QPK, D]
        acc_ref[...] = acc_ref[...] * alpha + pv.reshape(h, d)

    @pl.when(blk == n_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _use_paged_kernel(d):
    return _HAS_PALLAS and d in (64, 128, 256)


def paged_decode_attention(q, k_pool, v_pool, block_tables, ctx_lens, *,
                           scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           interpret: Optional[bool] = None):
    """Single-query paged attention: q [B, H, D] (one decode token per
    lane) over each lane's block table.  Pallas kernel where the head dim
    allows, masked-dense fallback elsewhere.  ctx_lens counts tokens
    already written to the pool INCLUDING the current one."""
    b, h, d = q.shape
    if use_kernel is None:
        use_kernel = (_use_paged_kernel(d)
                      and jax.default_backend() == "tpu")
    if not use_kernel:
        out = paged_attention_reference(
            q[:, None], k_pool, v_pool, block_tables, ctx_lens,
            (ctx_lens - 1)[:, None], scale=scale)
        return out[:, 0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb, bs, kh, _ = k_pool.shape
    mb = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    kernel = functools.partial(
        _paged_decode_kernel, block_size=bs, q_per_kv=h // kh,
        scale=scale, n_blocks=mb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # block tables + context lengths
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda i, j, bt, ln: (i, 0, 0)),
            pl.BlockSpec((None, bs, kh, d),
                         lambda i, j, bt, ln: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((None, bs, kh, d),
                         lambda i, j, bt, ln: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda i, j, bt, ln: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      q[:, None].reshape(b, h, d), k_pool, v_pool)
    return out


def paged_attention(q, k_pool, v_pool, block_tables, ctx_lens, q_positions,
                    *, scale: Optional[float] = None):
    """Dispatch paged attention for a [B, T, H, D] query slice: the T=1
    decode step rides the single-query kernel path, multi-token prefill
    chunks ride the masked-dense path."""
    if q.shape[1] == 1:
        return paged_decode_attention(
            q[:, 0], k_pool, v_pool, block_tables, ctx_lens,
            scale=scale)[:, None]
    return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                     ctx_lens, q_positions, scale=scale)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                     dq_acc, delta_ref, *, block_k: int, causal: bool,
                     scale: float, n_kv_blocks: int):
    """dq: grid (bh, q_block, kv_block) — kv streams, dq accumulates.
    ds = p * (dO V^T - D), dq = ds K * scale, with D = rowsum(dO * O)."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    bq = q_ref.shape[0]
    q_offset = q_idx * bq
    kv_offset = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        delta_ref[...] = jnp.sum(
            do_ref[...].astype(jnp.float32) * o_ref[...].astype(jnp.float32),
            axis=-1, keepdims=True)

    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        s = (q * scale) @ k_blk.T                     # [bq, bk]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                 # [bq, bk]
        dp = do @ v_blk.T                             # [bq, bk]
        ds = p * (dp - delta_ref[...])
        dq_acc[...] += (ds @ k_blk) * scale

    if causal:
        pl.when(q_offset + bq - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv_blocks - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                      causal: bool, scale: float, n_q_blocks: int):
    """dk/dv: grid (bh, kv_block, q_block) — q streams, dk/dv accumulate.
    dv = P^T dO;  dk = ds^T Q * scale."""
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(1)
    bk = k_ref.shape[0]
    q_offset = q_idx * block_q
    kv_offset = kv_idx * bk

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        delta = jnp.sum(do * o_ref[...].astype(jnp.float32),
                        axis=-1, keepdims=True)      # [bq, 1]
        s = (q * scale) @ k_blk.T                    # [bq, bk]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                # [bq, bk]
        dv_acc[...] += p.T @ do
        dp = do @ v_blk.T
        ds = p * (dp - delta)
        dk_acc[...] += (ds.T @ q) * scale

    if causal:
        pl.when(q_offset + block_q - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(q_idx == n_q_blocks - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward_impl(q, k, v, out, lse, g, causal, scale, block_q,
                         block_k, interpret):
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _fit_blocks(q_len, kv_len, block_q, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n_q_blocks = q_len // block_q
    n_kv_blocks = kv_len // block_k

    qr, kr, vr = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    dor, outr = _fold_heads(g), _fold_heads(out)

    q_spec = pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0))
    kv_spec = pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0))
    lse_spec = pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale, n_kv_blocks=n_kv_blocks),
        grid=(b * h, n_q_blocks, n_kv_blocks),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, q_len, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lse)

    # dkv sweep: middle grid dim = kv block (fixed per sweep), last = q.
    q_spec2 = pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, kk, 0))
    kv_spec2 = pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, j, 0))
    lse_spec2 = pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, kk, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale, n_q_blocks=n_q_blocks),
        grid=(b * h, n_kv_blocks, n_q_blocks),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2, lse_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * h, kv_len, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, kv_len, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lse)

    unfold = lambda x, l: x.reshape(b, h, l, d).transpose(0, 2, 1, 3)
    return unfold(dq, q_len), unfold(dk, kv_len), unfold(dv, kv_len)
