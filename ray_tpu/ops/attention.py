"""Attention ops: blockwise (flash) attention with a Pallas TPU kernel.

No reference counterpart — Ray delegates compute to hosted frameworks
(SURVEY.md §5 "Long-context: absent").  Here attention is a core op: the
Pallas kernel keeps the softmax accumulation in VMEM (online softmax, never
materialising the [L, L] score matrix in HBM) and tiles the contraction onto
the MXU; a pure-jnp fallback covers CPU tests and odd shapes.

Layouts: q/k/v are [batch, length, heads, head_dim] (BLHD) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        segment_ids=None) -> jax.Array:
    """Plain XLA attention (fallback + ground truth for kernel tests)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = _build_mask(q.shape[1], k.shape[1], causal, segment_ids)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _build_mask(q_len, k_len, causal, segment_ids):
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), bool),
                        k=k_len - q_len)[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask & seg)
    return mask


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                  acc_ref, *, block_k: int, causal: bool, scale: float,
                  n_kv_blocks: int):
    """One (batch*head, q_block, kv_block) grid step: online softmax.

    K/V arrive one VMEM block per grid step (the grid's last dim streams
    them from HBM — memory is O(block), not O(kv_len)); softmax state
    persists in VMEM scratch across the kv sweep for a given q block.
    Refs: q [bq, d], k/v [block_k, d], o [bq, d], lse [bq, 1] (saved for
    the backward); scratch m/l [bq, 1] f32, acc [bq, d] f32.
    """
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    bq = q_ref.shape[0]
    q_offset = q_idx * bq
    kv_offset = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        s = q @ k_blk.T                                        # [bq, block_k]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + p @ v_blk

    if causal:
        # KV blocks strictly above the diagonal contribute nothing.
        pl.when(q_offset + bq - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l_safe)


try:  # Pallas import kept lazy-safe for platforms without it.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 1024,
                    block_k: int = 1024, interpret: Optional[bool] = None):
    """Blockwise attention via Pallas.  Falls back to XLA attention when the
    shape does not tile (length % block != 0) or Pallas is unavailable.

    Differentiable end-to-end in Pallas: the forward saves (O, logsumexp)
    and the backward runs flash-style dq and dk/dv kernels (causal block
    skipping, f32 VMEM accumulators) — never materializing [L, L]."""
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                                 interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward_impl(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if lse is None:  # forward took the XLA fallback: recompute via XLA
        _, vjp = jax.vjp(
            lambda q, k, v: reference_attention(q, k, v, causal=causal,
                                                scale=scale), q, k, v)
        return vjp(g)
    return _flash_backward_impl(q, k, v, out, lse, g, causal, scale,
                                block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _use_pallas(q_len, kv_len, d, block_q, block_k, causal):
    return (_HAS_PALLAS and q_len % block_q == 0 and kv_len % block_k == 0
            and d in (64, 128, 256) and not (causal and q_len != kv_len))


def _fold_heads(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _fit_blocks(q_len, kv_len, block_q, block_k):
    """Clamp blocks to the lengths, then halve until they tile — lengths
    like 1536 must ride the Pallas path with 512-blocks rather than fall
    back to the [L,L]-materializing XLA reference."""
    block_q = min(block_q, q_len)
    block_k = min(block_k, kv_len)
    while block_q > 128 and q_len % block_q:
        block_q //= 2
    while block_k > 128 and kv_len % block_k:
        block_k //= 2
    return block_q, block_k


def _flash_forward_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _fit_blocks(q_len, kv_len, block_q, block_k)
    if not _use_pallas(q_len, kv_len, d, block_q, block_k, causal):
        return reference_attention(q, k, v, causal=causal, scale=scale), None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n_kv_blocks = kv_len // block_k

    # Fold batch and heads into the grid; kernel sees [len, d] slices.
    qr, kr, vr = _fold_heads(q), _fold_heads(k), _fold_heads(v)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               scale=scale, n_kv_blocks=n_kv_blocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, q_len // block_q, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, q_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, q_len, d).transpose(0, 2, 1, 3), lse


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                     dq_acc, delta_ref, *, block_k: int, causal: bool,
                     scale: float, n_kv_blocks: int):
    """dq: grid (bh, q_block, kv_block) — kv streams, dq accumulates.
    ds = p * (dO V^T - D), dq = ds K * scale, with D = rowsum(dO * O)."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    bq = q_ref.shape[0]
    q_offset = q_idx * bq
    kv_offset = kv_idx * block_k

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        delta_ref[...] = jnp.sum(
            do_ref[...].astype(jnp.float32) * o_ref[...].astype(jnp.float32),
            axis=-1, keepdims=True)

    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        s = (q * scale) @ k_blk.T                     # [bq, bk]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                 # [bq, bk]
        dp = do @ v_blk.T                             # [bq, bk]
        ds = p * (dp - delta_ref[...])
        dq_acc[...] += (ds @ k_blk) * scale

    if causal:
        pl.when(q_offset + bq - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv_blocks - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                      causal: bool, scale: float, n_q_blocks: int):
    """dk/dv: grid (bh, kv_block, q_block) — q streams, dk/dv accumulate.
    dv = P^T dO;  dk = ds^T Q * scale."""
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(1)
    bk = k_ref.shape[0]
    q_offset = q_idx * block_q
    kv_offset = kv_idx * bk

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        delta = jnp.sum(do * o_ref[...].astype(jnp.float32),
                        axis=-1, keepdims=True)      # [bq, 1]
        s = (q * scale) @ k_blk.T                    # [bq, bk]
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[...])                # [bq, bk]
        dv_acc[...] += p.T @ do
        dp = do @ v_blk.T
        ds = p * (dp - delta)
        dk_acc[...] += (ds.T @ q) * scale

    if causal:
        pl.when(q_offset + block_q - 1 >= kv_offset)(_compute)
    else:
        _compute()

    @pl.when(q_idx == n_q_blocks - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward_impl(q, k, v, out, lse, g, causal, scale, block_q,
                         block_k, interpret):
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _fit_blocks(q_len, kv_len, block_q, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n_q_blocks = q_len // block_q
    n_kv_blocks = kv_len // block_k

    qr, kr, vr = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    dor, outr = _fold_heads(g), _fold_heads(out)

    q_spec = pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, j, 0))
    kv_spec = pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, kk, 0))
    lse_spec = pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale, n_kv_blocks=n_kv_blocks),
        grid=(b * h, n_q_blocks, n_kv_blocks),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, q_len, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lse)

    # dkv sweep: middle grid dim = kv block (fixed per sweep), last = q.
    q_spec2 = pl.BlockSpec((None, block_q, d), lambda i, j, kk: (i, kk, 0))
    kv_spec2 = pl.BlockSpec((None, block_k, d), lambda i, j, kk: (i, j, 0))
    lse_spec2 = pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, kk, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale, n_q_blocks=n_q_blocks),
        grid=(b * h, n_kv_blocks, n_q_blocks),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2, lse_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * h, kv_len, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, kv_len, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, outr, lse)

    unfold = lambda x, l: x.reshape(b, h, l, d).transpose(0, 2, 1, 3)
    return unfold(dq, q_len), unfold(dk, kv_len), unfold(dv, kv_len)
