"""Fused chunked softmax cross-entropy over a large vocabulary.

No reference counterpart (Ray hosts frameworks; the loss lives here).
Motivation, measured on one v5e chip (PERF.md): computing GPT-2 logits
[B,L,V] then fp32 log_softmax materializes ~2.4GB of HBM traffic per
direction and ran the lm-head at ~10% MFU — ~100ms of a 130ms train step.

This op never materializes the full [T, V] logits: it scans over row
chunks, computing chunk logits -> logsumexp -> target gather on the fly,
and the custom VJP recomputes chunk logits in the backward (flash-attention
-style recompute, here for the classifier head).  Peak extra memory is one
[chunk, V] block instead of [T, V].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_cross_entropy(x, head, targets, valid, n_chunks: int = 4):
    """Mean masked NLL of `targets` under softmax(x @ head).

    x: [T, D] activations (bf16 ok); head: [D, V]; targets: [T] int;
    valid: [T] float mask.  Returns scalar fp32:
        sum(valid * nll) / max(sum(valid), 1).
    """
    loss, _ = _ce_fwd_impl(x, head, targets, valid, n_chunks)
    return loss


def _chunk(arr, n_chunks):
    t = arr.shape[0]
    c = t // n_chunks
    return arr[: c * n_chunks].reshape((n_chunks, c) + arr.shape[1:])


def _ce_fwd_impl(x, head, targets, valid, n_chunks):
    t = x.shape[0]
    if t % n_chunks:
        n_chunks = 1
    xs = _chunk(x, n_chunks)
    ts = _chunk(targets, n_chunks)
    vs = _chunk(valid, n_chunks)

    def body(acc, inp):
        x_c, t_c, v_c = inp
        # bf16 MXU matmul with fp32 accumulation — never an fp32 matmul
        # (8x slower on the MXU) and no separate [C, V] cast buffer.
        logits = jax.lax.dot(x_c, head,
                             preferred_element_type=jnp.float32)  # [C, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        # Row-gather of the target logit as a masked reduction — gathers/
        # scatters on [C, V] do not vectorize on TPU, iota compares do.
        iota_v = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        tgt = jnp.sum(jnp.where(iota_v == t_c[:, None].astype(jnp.int32),
                                logits, 0.0), axis=1)
        return acc + jnp.sum((lse - tgt) * v_c), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, vs),
                            unroll=True)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    return total / denom, denom


def _ce_fwd(x, head, targets, valid, n_chunks):
    loss, denom = _ce_fwd_impl(x, head, targets, valid, n_chunks)
    return loss, (x, head, targets, valid, denom)


def _ce_bwd(n_chunks, res, g):
    x, head, targets, valid, denom = res
    t, d = x.shape
    v = head.shape[1]
    nc = n_chunks if t % n_chunks == 0 else 1
    xs = _chunk(x, nc)
    ts = _chunk(targets, nc)
    vs = _chunk(valid, nc)
    scale = (g / denom).astype(jnp.float32)

    c = xs.shape[1]

    def body(dhead_acc, inp):
        x_c, t_c, v_c = inp
        logits = jax.lax.dot(x_c, head,
                             preferred_element_type=jnp.float32)  # [C, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        sv = v_c * scale                                  # [C]
        # dlogits = (softmax - onehot(t)) * sv as ONE fused elementwise
        # chain: exp, scale, and an iota-mask subtraction (a scatter here
        # would serialize on TPU).
        iota_v = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        is_tgt = iota_v == t_c[:, None].astype(jnp.int32)
        dlogits = ((jnp.exp(logits - lse[:, None])
                    - jnp.where(is_tgt, 1.0, 0.0))
                   * sv[:, None]).astype(x.dtype)         # [C, V] bf16
        dx_c = jax.lax.dot(dlogits, head.T.astype(x.dtype))   # [C, D]
        # bf16 x bf16 -> fp32 accumulate on the MXU for the head grad.
        dhead_acc = dhead_acc + jax.lax.dot(
            x_c.T, dlogits, preferred_element_type=jnp.float32)
        return dhead_acc, dx_c

    dhead, dxs = jax.lax.scan(
        body, jnp.zeros((d, v), jnp.float32), (xs, ts, vs), unroll=True)
    dx = dxs.reshape(t, d)
    return dx, dhead.astype(head.dtype), None, None


fused_cross_entropy.defvjp(_ce_fwd, _ce_bwd)


# ---------------------------------------------------------------------------
# SPMD variant: shard_map over the mesh, vocab-sharded logsumexp
# ---------------------------------------------------------------------------

ROW_AXES = ("data", "fsdp", "seq")   # mesh axes that shard rows (tokens)
VOCAB_AXIS = "tensor"                # mesh axis that shards the vocab dim


def spmd_ce_applicable(mesh, vocab: int, batch: int, length: int) -> bool:
    """The shard_map CE path needs the sharded dims to divide evenly."""
    if mesh is None:
        return False
    t = mesh.shape.get(VOCAB_AXIS, 1)
    rows = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    seq = mesh.shape.get("seq", 1)
    return vocab % t == 0 and batch % rows == 0 and length % seq == 0


def _spmd_rows(x_l, t_l, v_l, n_chunks):
    d = x_l.shape[-1]
    x2 = x_l.reshape(-1, d)
    t2 = t_l.reshape(-1).astype(jnp.int32)
    v2 = v_l.reshape(-1)
    nc = n_chunks if x2.shape[0] % n_chunks == 0 else 1
    return x2, t2, v2, nc


def _spmd_lse_tgt(logits, t_c, offset):
    """Vocab-sharded logsumexp + target-logit via psum over the tensor
    axis (max-shifted for stability)."""
    m = jax.lax.pmax(jnp.max(logits, axis=-1), VOCAB_AXIS)
    s = jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[:, None]), axis=-1), VOCAB_AXIS)
    lse = m + jnp.log(s)
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + offset
    tgt = jax.lax.psum(
        jnp.sum(jnp.where(iota == t_c[:, None], logits, 0.0), axis=1),
        VOCAB_AXIS)
    return lse, tgt, iota



from ray_tpu.parallel.mesh import shard_map_compat as _shard_map


def _vshard(mesh, head):
    return head.shape[1] // max(mesh.shape.get(VOCAB_AXIS, 1), 1)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_cross_entropy_spmd(x, head, targets, valid, mesh,
                             n_chunks: int = 4):
    """Mesh-parallel fused CE: never materializes [T, V] logits on ANY
    chip.  Rows (batch x length) shard over (data, fsdp, seq); the vocab
    dim of `head` shards over the tensor axis, with the logsumexp, target
    gather, and dx reduced across vocab shards by explicit psum/pmax —
    the distributed form of the chunked custom-VJP above.

    The custom VJP wraps AROUND the shard_map calls (fwd and bwd are each
    a forward-only shard_map), so shard_map's transpose semantics never
    enter the picture — every cross-shard reduction is an explicit
    collective in this file.

    x: [B, L, D]; head: [D, V]; targets/valid: [B, L].  Returns a
    replicated fp32 scalar.  Gradients flow to x and head only.
    """
    loss, _ = _spmd_fwd_call(x, head, targets, valid, mesh, n_chunks)
    return loss


def _spmd_fwd_call(x, head, targets, valid, mesh, n_chunks):
    from jax.sharding import PartitionSpec as P

    vshard = _vshard(mesh, head)

    def fwd_impl(x_l, head_l, t_l, v_l):
        x2, t2, v2, nc = _spmd_rows(x_l, t_l, v_l, n_chunks)
        offset = jax.lax.axis_index(VOCAB_AXIS) * vshard

        def body(acc, inp):
            x_c, t_c, v_c = inp
            logits = jax.lax.dot(x_c, head_l,
                                 preferred_element_type=jnp.float32)
            lse, tgt, _ = _spmd_lse_tgt(logits, t_c, offset)
            return acc + jnp.sum((lse - tgt) * v_c), None

        total, _ = jax.lax.scan(
            body, jnp.zeros((), jnp.float32),
            (_chunk(x2, nc), _chunk(t2, nc), _chunk(v2, nc)), unroll=True)
        total = jax.lax.psum(total, ROW_AXES + (VOCAB_AXIS,)) \
            / mesh.shape.get(VOCAB_AXIS, 1)
        denom = jnp.maximum(
            jax.lax.psum(jnp.sum(v2), ROW_AXES), 1.0)
        return total / denom, denom

    return _shard_map(
        fwd_impl, mesh,
        (P(("data", "fsdp"), "seq", None), P(None, VOCAB_AXIS),
         P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        (P(), P()),
    )(x, head, targets, valid)


def _ce_spmd_fwd(x, head, targets, valid, mesh, n_chunks):
    loss, denom = _spmd_fwd_call(x, head, targets, valid, mesh, n_chunks)
    return loss, (x, head, targets, valid, denom)


def _ce_spmd_bwd(mesh, n_chunks, res, g):
    from jax.sharding import PartitionSpec as P

    x, head, targets, valid, denom = res
    vshard = _vshard(mesh, head)
    scale_g = (g / denom).astype(jnp.float32)

    def bwd_impl(x_l, head_l, t_l, v_l, scale):
        x2, t2, v2, nc = _spmd_rows(x_l, t_l, v_l, n_chunks)
        d = x2.shape[1]
        offset = jax.lax.axis_index(VOCAB_AXIS) * vshard

        def body(dhead_acc, inp):
            x_c, t_c, v_c = inp
            logits = jax.lax.dot(x_c, head_l,
                                 preferred_element_type=jnp.float32)
            lse, _, iota = _spmd_lse_tgt(logits, t_c, offset)
            sv = v_c * scale
            dlogits = ((jnp.exp(logits - lse[:, None])
                        - jnp.where(iota == t_c[:, None], 1.0, 0.0))
                       * sv[:, None]).astype(x_l.dtype)
            # Partial over this vocab shard's columns; the tensor-axis
            # psum (once, after the scan) completes dx.
            dx_c = jax.lax.dot(dlogits, head_l.T.astype(x_l.dtype))
            dhead_acc = dhead_acc + jax.lax.dot(
                x_c.T, dlogits, preferred_element_type=jnp.float32)
            return dhead_acc, dx_c

        dhead_l, dxs = jax.lax.scan(
            body, jnp.zeros((d, head_l.shape[1]), jnp.float32),
            (_chunk(x2, nc), _chunk(t2, nc), _chunk(v2, nc)), unroll=True)
        dx_l = jax.lax.psum(dxs.reshape(x_l.shape), VOCAB_AXIS)
        # Rows are disjoint across (data, fsdp, seq): psum completes the
        # row-sum, leaving dhead replicated there and vocab-sharded.
        dhead_l = jax.lax.psum(dhead_l, ROW_AXES).astype(head_l.dtype)
        return dx_l, dhead_l

    dx, dhead = _shard_map(
        bwd_impl, mesh,
        (P(("data", "fsdp"), "seq", None), P(None, VOCAB_AXIS),
         P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq"), P()),
        (P(("data", "fsdp"), "seq", None), P(None, VOCAB_AXIS)),
    )(x, head, targets, valid, scale_g)
    return dx, dhead, None, None


fused_cross_entropy_spmd.defvjp(_ce_spmd_fwd, _ce_spmd_bwd)
