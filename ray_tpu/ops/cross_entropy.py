"""Fused chunked softmax cross-entropy over a large vocabulary.

No reference counterpart (Ray hosts frameworks; the loss lives here).
Motivation, measured on one v5e chip (PERF.md): computing GPT-2 logits
[B,L,V] then fp32 log_softmax materializes ~2.4GB of HBM traffic per
direction and ran the lm-head at ~10% MFU — ~100ms of a 130ms train step.

This op never materializes the full [T, V] logits: it scans over row
chunks, computing chunk logits -> logsumexp -> target gather on the fly,
and the custom VJP recomputes chunk logits in the backward (flash-attention
-style recompute, here for the classifier head).  Peak extra memory is one
[chunk, V] block instead of [T, V].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_cross_entropy(x, head, targets, valid, n_chunks: int = 4):
    """Mean masked NLL of `targets` under softmax(x @ head).

    x: [T, D] activations (bf16 ok); head: [D, V]; targets: [T] int;
    valid: [T] float mask.  Returns scalar fp32:
        sum(valid * nll) / max(sum(valid), 1).
    """
    loss, _ = _ce_fwd_impl(x, head, targets, valid, n_chunks)
    return loss


def _chunk(arr, n_chunks):
    t = arr.shape[0]
    c = t // n_chunks
    return arr[: c * n_chunks].reshape((n_chunks, c) + arr.shape[1:])


def _ce_fwd_impl(x, head, targets, valid, n_chunks):
    t = x.shape[0]
    if t % n_chunks:
        n_chunks = 1
    xs = _chunk(x, n_chunks)
    ts = _chunk(targets, n_chunks)
    vs = _chunk(valid, n_chunks)

    def body(acc, inp):
        x_c, t_c, v_c = inp
        # bf16 MXU matmul with fp32 accumulation — never an fp32 matmul
        # (8x slower on the MXU) and no separate [C, V] cast buffer.
        logits = jax.lax.dot(x_c, head,
                             preferred_element_type=jnp.float32)  # [C, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        # Row-gather of the target logit as a masked reduction — gathers/
        # scatters on [C, V] do not vectorize on TPU, iota compares do.
        iota_v = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        tgt = jnp.sum(jnp.where(iota_v == t_c[:, None].astype(jnp.int32),
                                logits, 0.0), axis=1)
        return acc + jnp.sum((lse - tgt) * v_c), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, vs),
                            unroll=True)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    return total / denom, denom


def _ce_fwd(x, head, targets, valid, n_chunks):
    loss, denom = _ce_fwd_impl(x, head, targets, valid, n_chunks)
    return loss, (x, head, targets, valid, denom)


def _ce_bwd(n_chunks, res, g):
    x, head, targets, valid, denom = res
    t, d = x.shape
    v = head.shape[1]
    nc = n_chunks if t % n_chunks == 0 else 1
    xs = _chunk(x, nc)
    ts = _chunk(targets, nc)
    vs = _chunk(valid, nc)
    scale = (g / denom).astype(jnp.float32)

    c = xs.shape[1]

    def body(dhead_acc, inp):
        x_c, t_c, v_c = inp
        logits = jax.lax.dot(x_c, head,
                             preferred_element_type=jnp.float32)  # [C, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        sv = v_c * scale                                  # [C]
        # dlogits = (softmax - onehot(t)) * sv as ONE fused elementwise
        # chain: exp, scale, and an iota-mask subtraction (a scatter here
        # would serialize on TPU).
        iota_v = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        is_tgt = iota_v == t_c[:, None].astype(jnp.int32)
        dlogits = ((jnp.exp(logits - lse[:, None])
                    - jnp.where(is_tgt, 1.0, 0.0))
                   * sv[:, None]).astype(x.dtype)         # [C, V] bf16
        dx_c = jax.lax.dot(dlogits, head.T.astype(x.dtype))   # [C, D]
        # bf16 x bf16 -> fp32 accumulate on the MXU for the head grad.
        dhead_acc = dhead_acc + jax.lax.dot(
            x_c.T, dlogits, preferred_element_type=jnp.float32)
        return dhead_acc, dx_c

    dhead, dxs = jax.lax.scan(
        body, jnp.zeros((d, v), jnp.float32), (xs, ts, vs), unroll=True)
    dx = dxs.reshape(t, d)
    return dx, dhead.astype(head.dtype), None, None


fused_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
