"""TPU-native ops: Pallas kernels and sharded attention primitives."""

from ray_tpu.ops.attention import flash_attention, reference_attention  # noqa: F401
from ray_tpu.ops.ring_attention import ring_attention  # noqa: F401
