"""TPU-native ops: Pallas kernels and sharded attention primitives."""

from ray_tpu.ops.attention import (  # noqa: F401
    flash_attention, paged_attention, paged_attention_reference,
    paged_decode_attention, paged_kv_update, reference_attention)
from ray_tpu.ops.ring_attention import ring_attention  # noqa: F401
