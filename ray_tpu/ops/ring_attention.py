"""Ring attention: exact attention over sequences sharded on a mesh axis.

No reference counterpart (SURVEY.md §2.5: sequence parallelism ABSENT in
Ray).  TPU-native design: each device holds a contiguous sequence shard of
q/k/v; K/V blocks rotate around the ring with `jax.lax.ppermute` (single-hop
ICI) while each device accumulates its shard's online-softmax state — compute
on block i overlaps the transfer of block i+1, so ICI time hides behind MXU
time for large enough shards.  Wraps to plain flash attention on a 1-device
axis.

Causal masking with sequence shards: device r holds positions
[r*S, (r+1)*S); a KV block that originated at ring slot s is entirely in the
past iff s < r, entirely in the future iff s > r, and diagonal iff s == r.
Past blocks need no mask, future blocks are skipped (their contribution is
fully masked), the diagonal block uses the local causal mask.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF
from ray_tpu.parallel.mesh import shard_map_compat


def _block_attend(q, k, v, scale, mask):
    """One q-shard x kv-block contribution: returns (m, l, acc) partials.
    q [B,Lq,H,D], k/v [B,Lk,H,D]; mask [Lq,Lk] bool or None.  acc stays
    float32 across merges (matches the Pallas kernel's f32 accumulator)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Lq]
    # Guard fully-masked rows (m == NEG_INF) against exp overflow/NaN.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)                                   # [B,H,Lq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)      # [B,Lq,H,D]
    return m, l, acc


def _merge(m1, l1, a1, m2, l2, a2):
    """Combine two online-softmax partial states (all f32)."""
    m = jnp.maximum(m1, m2)
    e1 = jnp.exp(m1 - m)
    e2 = jnp.exp(m2 - m)
    l = l1 * e1 + l2 * e2
    # e* are [B,H,Lq]; acc is [B,Lq,H,D] — transpose scale factors.
    s1 = e1.transpose(0, 2, 1)[..., None]
    s2 = e2.transpose(0, 2, 1)[..., None]
    a = a1 * s1 + a2 * s2
    return m, l, a


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = "seq",
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact (flash-equivalent) attention with q/k/v sequence-sharded over
    mesh `axis`.  Inputs/outputs are global arrays [B, L, H, D]; sharding of
    the length dim over `axis` is applied via shard_map.
    """
    from ray_tpu.parallel.mesh import mesh_axis_size
    from ray_tpu.parallel.sharding import DEFAULT_RULES

    n_ring = mesh_axis_size(mesh, axis)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if n_ring == 1:
        from ray_tpu.ops.attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)

    # Batch stays sharded over the data axes and heads over tensor — only
    # the length dim participates in the ring (otherwise every DP replica
    # would recompute the full global batch).
    def _mapped(name):
        ax = DEFAULT_RULES.get(name)
        axes = (ax,) if isinstance(ax, str) else tuple(ax or ())
        axes = tuple(a for a in axes if mesh_axis_size(mesh, a) > 1)
        return None if not axes else (axes[0] if len(axes) == 1 else axes)

    spec = P(_mapped("batch"), axis, _mapped("heads"), None)

    def local(qs, ks, vs):
        r = jax.lax.axis_index(axis)
        lq = qs.shape[1]
        causal_mask = jnp.tril(jnp.ones((lq, lq), bool)) if causal else None

        B, _, H, D = qs.shape
        perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]

        # Block 0: the local (diagonal) KV shard — no transfer needed.
        m, l, acc = _block_attend(qs, ks, vs, scale,
                                  causal_mask if causal else None)

        def step(carry, i):
            m, l, acc, kb, vb = carry
            # Rotate first: after i rotations we hold the KV shard that
            # originated at ring slot (r - i) mod n.  Exactly n_ring - 1
            # rotations happen in total (no wasted final hop).
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            src = (r - i) % n_ring
            if causal:
                def past(_):
                    return _block_attend(qs, kb, vb, scale, None)

                def future(_):
                    return (jnp.full_like(m, NEG_INF), jnp.zeros_like(l),
                            jnp.zeros_like(acc))

                bm, bl, ba = jax.lax.cond(src < r, past, future, None)
            else:
                bm, bl, ba = _block_attend(qs, kb, vb, scale, None)
            m, l, acc = _merge(m, l, acc, bm, bl, ba)
            return (m, l, acc, kb, vb), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (m, l, acc, ks, vs), jnp.arange(1, n_ring))
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return (acc.astype(jnp.float32) / denom).astype(qs.dtype)

    fn = shard_map_compat(local, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)
