"""Public API surface.

Reference parity: python/ray/_private/worker.py (ray.init:1108, get:2411,
put:2544, wait:2606, remote:3034, kill:2763, get_actor:2728, shutdown),
python/ray/remote_function.py and python/ray/actor.py (@remote wrapping,
.options(), ActorHandle/ActorMethod).
"""

from __future__ import annotations

import atexit
import functools
import inspect
import threading

from ray_tpu.exceptions import ActorDiedError
from ray_tpu.object_ref import ObjectRef
from ray_tpu._private.ids import ActorID, JobID
from ray_tpu._private.protocol import validate_options

_global_lock = threading.Lock()
_worker = None          # CoreWorker of this process (driver or task worker)
_cluster = None         # dict describing processes we spawned (head only)


def is_initialized() -> bool:
    return _worker is not None


def _get_worker():
    global _worker
    if _worker is None:
        # Inside a task-executing worker process the core worker already
        # exists; find it via the worker_main-installed global.
        raise RuntimeError("ray_tpu.init() has not been called")
    return _worker


def init(address: str | None = None, *, num_cpus=None, num_tpus=None,
         resources=None, namespace: str = "default",
         object_store_memory: int = 256 << 20, ignore_reinit_error=False,
         log_to_driver: bool = True, _system_config=None):
    """Connect to (or bootstrap) a cluster.  Reference: worker.py ray.init:1108."""
    global _worker, _cluster
    if address is None:
        # Reference parity: RAY_ADDRESS lets submitted job drivers join the
        # cluster that launched them (job_manager.py sets it on entrypoints).
        import os as _os0
        address = _os0.environ.get("RAY_TPU_ADDRESS") or None
    with _global_lock:
        if _worker is not None:
            if ignore_reinit_error:
                return _connection_info()
            raise RuntimeError("ray_tpu.init() called twice")
        from ray_tpu._private.config import GLOBAL_CONFIG
        GLOBAL_CONFIG.apply_system_config(_system_config)
        # Spawned daemons inherit overrides through the env (reference:
        # _system_config forwarded to gcs/raylet at bootstrap); shutdown()
        # undoes both so config can't leak into a later init().
        import os as _os
        global _applied_system_config
        _applied_system_config = list(_system_config or {})
        for k, v in (_system_config or {}).items():
            _os.environ[f"RAY_TPU_{k.upper()}"] = str(v)
        if address and address.startswith("ray_tpu://"):
            # Thin-client mode (reference: Ray Client, ray://): no local
            # store/daemons — every call proxies to the client server.
            from ray_tpu.util.client import ClientWorker
            _worker = ClientWorker(address[len("ray_tpu://"):])
            _cluster = {"group": None, "gcs": address, "owned": False}
            if log_to_driver:
                _start_log_echo(_worker)
            atexit.register(shutdown)
            return _connection_info()

        from ray_tpu._private import node as node_mod
        from ray_tpu._private.core_worker import CoreWorker
        from ray_tpu._private.rpc import RpcClient

        group = None
        if address is None:
            session_dir = node_mod.new_session_dir()
            group = node_mod.ProcessGroup()
            try:
                gcs_address = node_mod.start_gcs(session_dir, group, watch_parent=True)
                head = node_mod.start_hostd(
                    gcs_address, session_dir, group,
                    num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
                    store_capacity=object_store_memory, head=True)
            except Exception:
                group.reap()
                raise
            _cluster = {"group": group, "gcs": gcs_address,
                        "session_dir": session_dir, "owned": True}
            from ray_tpu._private import usage as _usage
            _usage.record_usage(session_dir)
        else:
            gcs_address = address
            # Find a hostd on this machine to use as our home node.
            import asyncio

            async def find_home():
                gcs = RpcClient(gcs_address)
                try:
                    reply = await gcs.call("Gcs", "get_nodes", {}, timeout=10)
                finally:
                    await gcs.close()
                import socket
                hostname = socket.gethostname()
                alive = [n for n in reply["nodes"] if n.alive]
                for n in alive:
                    if n.hostname == hostname:
                        return n
                raise RuntimeError(
                    "no alive node on this host; start one with "
                    "`ray_tpu start --address=...`")
            head_info = asyncio.run(find_home())
            head = {"address": head_info.address,
                    "node_id": head_info.node_id.hex(),
                    "store_path": head_info.store_path}
            _cluster = {"group": None, "gcs": gcs_address, "owned": False}

        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.rpc import RpcClient as _Rpc
        import asyncio as _aio

        try:
            async def next_job():
                gcs = _Rpc(gcs_address)
                try:
                    reply = await gcs.call("Gcs", "next_job_id", {}, timeout=10)
                    return reply["job_id"]
                finally:
                    await gcs.close()
            job_int = _aio.run(next_job())

            _worker = CoreWorker(
                mode="driver",
                gcs_address=gcs_address,
                store_path=head["store_path"],
                node_id=NodeID.from_hex(head["node_id"]),
                hostd_address=head["address"],
                job_id=JobID(job_int.to_bytes(4, "little")),
            )
        except Exception:
            _cluster = None
            if group is not None:
                group.reap()
            raise
        if log_to_driver:
            _start_log_echo(_worker)
        _start_driver_telemetry()
        atexit.register(shutdown)
        return _connection_info()


_log_echo_stop = None
_telemetry = None


def _start_driver_telemetry():
    """Driver-process pull endpoints (/metrics /events /healthz): serve
    routers, train drivers, and user Counters record in THIS process,
    which no hostd scrapes — the driver exports its own."""
    global _telemetry
    import time as _time

    from ray_tpu.util import metrics as mt
    from ray_tpu.util import telemetry

    # A lean driver may never touch a library Counter, and an empty
    # /metrics body reads as a broken scrape — always export uptime.
    up = mt.Gauge("driver_uptime_seconds", "seconds since ray_tpu.init")
    t0 = _time.time()

    def metrics_fn():
        up.set(_time.time() - t0)
        return mt.prometheus_text(mt.collect(), {"component": "driver"})

    def events_fn(plane, kind, trace_id, since):
        from ray_tpu.util import events as ev
        return [e for e in ev.snapshot(since=since, plane=plane, kind=kind)
                if trace_id is None or e.get("trace_id") == trace_id]

    _telemetry = telemetry.start_server(
        metrics_fn=metrics_fn, events_fn=events_fn, component="driver")


def _start_log_echo(worker):
    """Echo worker stdout/stderr to the driver terminal (reference:
    worker.py log streaming via GCS pubsub; prefix = (pid, stream))."""
    global _log_echo_stop
    import sys
    import threading as _th
    import time as _time

    stop = _th.Event()
    _log_echo_stop = stop
    job = worker._job_int()

    def loop():
        after = 0
        while not stop.is_set():
            _time.sleep(0.5)
            coro = worker.gcs.call(
                "Gcs", "get_log_lines",
                {"after_seq": after, "job_id": job}, timeout=10)
            try:
                reply = worker.io.run(coro, timeout=15)
            except RuntimeError:
                # Loop gone before scheduling: the coroutine never ran —
                # closing is safe and silences the never-awaited warning.
                coro.close()
                continue
            except Exception:
                # Scheduled but failed/timed out: the loop owns the
                # coroutine — closing from this thread would race it.
                continue
            # Advance past EVERYTHING the GCS scanned (global seq), not
            # just this job's lines, or quiet jobs rescan the whole ring.
            after = max(after, reply.get("seq", after))
            try:
                for seq, rec in reply.get("lines", []):
                    out = (sys.stderr if rec["stream"] == "stderr"
                           else sys.stdout)
                    print(f"(pid={rec['pid']}) {rec['line']}", file=out)
            except (BrokenPipeError, OSError):
                return  # stdout gone (piped driver exited) — stop echoing
            except Exception:
                pass

    _th.Thread(target=loop, daemon=True, name="raytpu-log-echo").start()


def _connection_info():
    return {"gcs_address": _cluster["gcs"] if _cluster else None,
            "session_dir": (_cluster or {}).get("session_dir")}


_applied_system_config: list = []


def shutdown():
    """Disconnect; if we bootstrapped the cluster, tear it down."""
    global _worker, _cluster, _applied_system_config, _log_echo_stop, \
        _telemetry
    if _log_echo_stop is not None:
        _log_echo_stop.set()
        _log_echo_stop = None
    if _telemetry is not None:
        _telemetry.stop()
        _telemetry = None
    with _global_lock:
        if _worker is None:
            return
        cluster, worker = _cluster, _worker
        _worker = None
        _cluster = None
        import os as _os

        from ray_tpu._private.config import GLOBAL_CONFIG
        for k in _applied_system_config:
            GLOBAL_CONFIG._overrides.pop(k, None)
            _os.environ.pop(f"RAY_TPU_{k.upper()}", None)
        if _applied_system_config:
            # Resolved values are cached on read; dropping the overrides
            # without this would leak them into a later init().
            GLOBAL_CONFIG.invalidate_cache()
            from ray_tpu._private import fault_injection
            fault_injection.reset()
        _applied_system_config = []
    try:
        if cluster and cluster.get("owned"):
            try:
                worker.io.run(worker.gcs.call("Gcs", "shutdown_cluster", {}),
                              timeout=5)
            except Exception:
                pass
    finally:
        worker.shutdown()
        if cluster and cluster.get("owned") and cluster.get("group"):
            cluster["group"].reap()


def put(value) -> ObjectRef:
    return _get_worker().put(value)


def get(refs, *, timeout: float | None = None):
    return _get_worker().get(refs, timeout)


def wait(refs, *, num_returns: int = 1, timeout: float | None = None,
         fetch_local: bool = True):
    if not isinstance(refs, list):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _get_worker().wait(refs, num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    _get_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref, *, force: bool = False, recursive: bool = True):
    """Cancel a pending or running task (reference: worker.py
    ray.cancel:2793).  force=False interrupts the running task with
    TaskCancelledError; force=True kills the executing worker process.

    recursive=True is accepted for reference compatibility, but
    cancellation is NOT yet propagated to child tasks spawned by the
    cancelled task — a warning is logged when this could matter.
    """
    if not isinstance(ref, ObjectRef):
        raise TypeError("cancel() expects an ObjectRef")
    if recursive:
        global _warned_recursive_cancel
        if not _warned_recursive_cancel:
            _warned_recursive_cancel = True
            import logging
            logging.getLogger("ray_tpu").warning(
                "cancel(recursive=True): child-task cancellation is not "
                "yet propagated; only the target task is cancelled")
    _get_worker().cancel_task(ref, force, recursive)


_warned_recursive_cancel = False


def get_actor(name: str, namespace: str = "default") -> "ActorHandle":
    info = _get_worker().get_named_actor(name, namespace)
    if info is None or info.state == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace {namespace!r}")
    return ActorHandle(info.actor_id, info.class_name, None)


def cluster_resources() -> dict:
    w = _get_worker()
    return w.io.run(w.gcs.call("Gcs", "cluster_resources", {}))["total"]


def available_resources() -> dict:
    w = _get_worker()
    return w.io.run(w.gcs.call("Gcs", "cluster_resources", {}))["available"]


def nodes() -> list:
    w = _get_worker()
    reply = w.io.run(w.gcs.call("Gcs", "get_nodes", {}))
    return [
        {"NodeID": n.node_id.hex(), "Alive": n.alive, "Address": n.address,
         "Resources": n.resources_total, "IsHead": n.is_head}
        for n in reply["nodes"]
    ]


# ---------------------------------------------------------------------------
# @remote
# ---------------------------------------------------------------------------


class RemoteFunction:
    def __init__(self, fn, options: dict):
        self._fn = fn
        self._options = validate_options(options, for_actor=False)
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        refs = _get_worker().submit_task(self._fn, args, kwargs, self._options)
        return refs[0] if self._options.get("num_returns", 1) == 1 else refs

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(opts)  # constructor re-validates the merged set
        return RemoteFunction(self._fn, merged)

    def bind(self, *args, **kwargs):
        """Lazy DAG authoring (reference: dag/function_node.py)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__} cannot be called directly; "
            f"use .remote()")


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        refs = _get_worker().submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            {"num_returns": self._num_returns,
             "max_task_retries": self._handle._max_task_retries})
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns: int = 1, **_):
        return ActorMethod(self._handle, self._name, num_returns)


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 method_meta: dict | None, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta or {}
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name,
                           self._method_meta.get(name, {}).get("num_returns", 1))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_meta, self._max_task_retries))


class ActorClass:
    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = validate_options(options, for_actor=True)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = _get_worker()
        actor_id = worker.create_actor(self._cls, args, kwargs, self._options)
        meta = {}
        for name, fn in inspect.getmembers(self._cls, inspect.isfunction):
            meta[name] = {"num_returns": 1}
        return ActorHandle(actor_id, self._cls.__name__, meta,
                           self._options.get("max_task_retries", 0))

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def bind(self, *args, **kwargs):
        """Lazy DAG authoring (reference: dag/class_node.py)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(f"actor class {self._cls.__name__} cannot be "
                        f"instantiated directly; use .remote()")


def remote(*args, **kwargs):
    """@remote decorator for tasks and actors (reference: worker.py:3034)."""
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0])
                                          or inspect.isclass(args[0])):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote options must be keyword arguments")

    def wrap(obj):
        return _make_remote(obj, kwargs)
    return wrap


def _make_remote(obj, options: dict):
    if inspect.isclass(obj):
        return ActorClass(obj, options)
    if inspect.isfunction(obj) or callable(obj):
        return RemoteFunction(obj, options)
    raise TypeError(f"@remote cannot wrap {obj!r}")


def method(num_returns: int = 1):
    """@method decorator inside actor classes (num_returns for methods)."""
    def wrap(fn):
        fn._num_returns = num_returns
        return fn
    return wrap
