"""ctypes client for the native shared-memory object store.

Equivalent of the reference's plasma client
(/root/reference/src/ray/object_manager/plasma/client.cc) but with no socket
protocol: the store state lives in shared memory and every operation is a
direct call into libtpustore.so (see objstore.cc for the design rationale).

Zero-copy: `get()` returns memoryviews straight into the mapped segment.
The serialization layer builds numpy arrays over them with np.frombuffer,
which jax.device_put consumes without an extra host copy.
"""

from __future__ import annotations

import ctypes
import os

from ray_tpu import _native
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError, RayTpuTimeoutError

_ID_SIZE = 28  # kIdSize in _native/objstore.cc

_OK = 0
_EXISTS = -1
_NOT_FOUND = -2
_OOM = -3
_TIMEOUT = -4
_BAD_STATE = -5
_SYS = -6

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_native.lib_path("tpustore"))
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.tpus_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_uint32, ctypes.POINTER(ctypes.c_void_p)]
        lib.tpus_attach.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.tpus_close.argtypes = [ctypes.c_void_p]
        lib.tpus_close.restype = None
        lib.tpus_destroy.argtypes = [ctypes.c_char_p]
        lib.tpus_base.argtypes = [ctypes.c_void_p]
        lib.tpus_base.restype = ctypes.c_void_p
        lib.tpus_obj_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_uint64, u64p]
        lib.tpus_obj_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpus_obj_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpus_obj_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, u64p, u64p, u64p]
        lib.tpus_obj_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpus_obj_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpus_obj_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpus_reclaim.argtypes = [ctypes.c_void_p]
        lib.tpus_stats.argtypes = [ctypes.c_void_p, u64p, u64p, u64p, u64p]
        lib.tpus_set_eviction.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tpus_list.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), u64p,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint32),
            u64p, ctypes.c_uint32]
        _lib = lib
    return _lib


class StoreBuffer:
    """A sealed object's data+metadata views plus the ref keeping them pinned."""

    __slots__ = ("store", "object_id", "data", "metadata", "_released")

    def __init__(self, store, object_id, data, metadata):
        self.store = store
        self.object_id = object_id
        self.data = data
        self.metadata = metadata
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.data = None
            self.metadata = None
            self.store._release(self.object_id)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class ObjectStore:
    """One per node; the node daemon creates it, workers attach."""

    def __init__(self, path: str, handle, view: memoryview, owner: bool):
        self.path = path
        self._h = handle
        self._view = view
        self._owner = owner
        self._closed = False

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, path: str, capacity_bytes: int, max_objects: int = 1 << 16):
        lib = _load()
        h = ctypes.c_void_p()
        rc = lib.tpus_create(path.encode(), capacity_bytes, max_objects,
                             ctypes.byref(h))
        _check(rc, "create store")
        return cls(path, h, _map_view(lib, h), owner=True)

    @classmethod
    def attach(cls, path: str):
        lib = _load()
        h = ctypes.c_void_p()
        rc = lib.tpus_attach(path.encode(), ctypes.byref(h))
        _check(rc, "attach store")
        return cls(path, h, _map_view(lib, h), owner=False)

    def close(self):
        if not self._closed:
            self._closed = True
            self._view = None
            _load().tpus_close(self._h)
            if self._owner:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass

    # -- object ops ----------------------------------------------------------

    def create_object(self, object_id: ObjectID, data_size: int,
                      metadata: bytes = b"") -> memoryview:
        """Allocate an unsealed object; returns a writable view of the data
        region. Caller writes into it and then calls seal()."""
        lib = _load()
        off = ctypes.c_uint64()
        rc = lib.tpus_obj_create(self._h, object_id.binary(), data_size,
                                 len(metadata), ctypes.byref(off))
        if rc == _OOM:
            raise ObjectStoreFullError(
                f"cannot allocate {data_size} bytes (capacity {self.stats()['capacity']})")
        _check(rc, f"create {object_id}")
        base = off.value
        if metadata:
            self._view[base + data_size: base + data_size + len(metadata)] = metadata
        return self._view[base: base + data_size]

    def put_bytes(self, object_id: ObjectID, data: bytes, metadata: bytes = b""):
        buf = self.create_object(object_id, len(data), metadata)
        buf[:] = data
        self.seal(object_id)

    def seal(self, object_id: ObjectID):
        _check(_load().tpus_obj_seal(self._h, object_id.binary()),
               f"seal {object_id}")
        from ray_tpu.util import events
        events.record("object", "seal", oid=object_id.binary().hex()[:16])

    def abort(self, object_id: ObjectID):
        _load().tpus_obj_abort(self._h, object_id.binary())

    def get(self, object_id: ObjectID, timeout_ms: int = 0) -> StoreBuffer | None:
        """Returns pinned zero-copy views, or None when absent (timeout_ms=0)
        / raises RayTpuTimeoutError (timeout_ms>0).  timeout_ms=-1 blocks."""
        lib = _load()
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        msize = ctypes.c_uint64()
        tok = None
        if timeout_ms != 0:
            # Blocking gets are a real wait phase (producer hasn't sealed
            # yet); zero-timeout polls stay span-free.
            from ray_tpu.util import spans
            tok = spans.begin("object", "store_wait",
                              oid=object_id.binary().hex()[:16])
        rc = lib.tpus_obj_get(self._h, object_id.binary(), timeout_ms,
                              ctypes.byref(off), ctypes.byref(size),
                              ctypes.byref(msize))
        if tok is not None:
            from ray_tpu.util import spans
            spans.end(tok, found=rc not in (_NOT_FOUND, _BAD_STATE,
                                            _TIMEOUT))
        if rc in (_NOT_FOUND, _BAD_STATE):
            return None
        if rc == _TIMEOUT:
            raise RayTpuTimeoutError(f"get({object_id}) timed out")
        _check(rc, f"get {object_id}")
        base, n, m = off.value, size.value, msize.value
        return StoreBuffer(self, object_id,
                           self._view[base: base + n],
                           bytes(self._view[base + n: base + n + m]))

    def _release(self, object_id: ObjectID):
        if not self._closed:
            _load().tpus_obj_release(self._h, object_id.binary())

    def delete(self, object_id: ObjectID):
        _load().tpus_obj_delete(self._h, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        rc = _load().tpus_obj_contains(self._h, object_id.binary())
        _check(min(rc, 0), f"contains {object_id}")
        return rc == 1

    def reclaim_dead_clients(self) -> bool:
        """Drop refs and unsealed creations of clients whose process died.
        Also runs automatically when an allocation fails."""
        return _load().tpus_reclaim(self._h) == 1

    def set_eviction(self, enabled: bool) -> None:
        """Toggle LRU eviction.  Spilling daemons disable it and reclaim
        space by spilling to disk instead (reference: plasma pins primary
        copies; raylet LocalObjectManager spills them)."""
        _check(_load().tpus_set_eviction(self._h, 1 if enabled else 0),
               "set_eviction")

    def list_objects(self, max_n: int = 65536) -> list:
        """Enumerate live objects: [(ObjectID, total_size, refcount,
        sealed, lru_tick)], oldest-first by lru_tick."""
        lib = _load()
        ids = (ctypes.c_uint8 * (_ID_SIZE * max_n))()
        sizes = (ctypes.c_uint64 * max_n)()
        refs = (ctypes.c_int32 * max_n)()
        states = (ctypes.c_uint32 * max_n)()
        ticks = (ctypes.c_uint64 * max_n)()
        n = lib.tpus_list(self._h, ids,
                          ctypes.cast(sizes, ctypes.POINTER(ctypes.c_uint64)),
                          ctypes.cast(refs, ctypes.POINTER(ctypes.c_int32)),
                          ctypes.cast(states, ctypes.POINTER(ctypes.c_uint32)),
                          ctypes.cast(ticks, ctypes.POINTER(ctypes.c_uint64)),
                          max_n)
        _check(min(n, 0), "list")
        out = []
        raw = bytes(ids)
        for i in range(n):
            out.append((ObjectID(raw[_ID_SIZE * i:_ID_SIZE * (i + 1)]), sizes[i],
                        refs[i], states[i] == 2, ticks[i]))
        out.sort(key=lambda e: e[4])
        return out

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        count = ctypes.c_uint64()
        ev = ctypes.c_uint64()
        _check(_load().tpus_stats(self._h, ctypes.byref(cap), ctypes.byref(used),
                                  ctypes.byref(count), ctypes.byref(ev)), "stats")
        return {"capacity": cap.value, "used": used.value,
                "num_objects": count.value, "num_evictions": ev.value}


def _map_view(lib, h) -> memoryview:
    import mmap as _  # noqa: F401  (documentation: base points into an mmap)
    base = lib.tpus_base(h)
    # Build a memoryview over the raw mapping.  The segment never moves or
    # shrinks while the handle is open, so this is safe.
    # Size: read the header's total_size (second u64 of the header).
    total = ctypes.cast(base + 8, ctypes.POINTER(ctypes.c_uint64)).contents.value
    arr = (ctypes.c_ubyte * total).from_address(base)
    return memoryview(arr).cast("B")


def _check(rc: int, what: str):
    if rc == _OK:
        return
    msg = {_EXISTS: "already exists", _NOT_FOUND: "not found", _OOM: "out of memory",
           _TIMEOUT: "timeout", _BAD_STATE: "bad state", _SYS: "system error"}.get(rc, rc)
    if rc == _OOM:
        raise ObjectStoreFullError(f"{what}: {msg}")
    raise RuntimeError(f"object store: {what}: {msg}")
