"""Python half of the native TaskSpec codec.

Reference parity: src/ray/common/task/task_spec.h + task_util.h
(TaskSpecBuilder) — the reference builds the TaskSpec protobuf in C++
and submission never serializes through Python.  Here the split is:

- Python builds a per-(fn, options) *template* once: the serialized
  constant fields of a TaskSpecP (protocol/raytpu.proto), registered
  with the native client (taskrpc.cc tpt_register_template).
- Per task, `pack_desc` packs a flat binary descriptor (ids, args,
  seq) — a handful of struct.packs, no pickle — and the native library
  splices template + descriptor into PushTaskRequest wire bytes
  (tpt_send_specs).
- The worker parses the proto with upb (C) and rebuilds the runtime's
  TaskSpec dataclass; replies travel as PushTaskReply protos.

The typed IDL is therefore the live wire contract on the task hot
path, not test-only freight: a non-Python peer can submit or serve
tasks by speaking TaskSpecP/PushTaskRequest directly.
"""

from __future__ import annotations

import json
import pickle
import struct

from ray_tpu.protocol import pb
from ray_tpu.protocol.convert import taskspec_to_proto
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    PlacementGroupID,
    TaskID,
)
from ray_tpu._private.protocol import RefArg, Resources, TaskSpec, ValueArg

_HDR = struct.Struct("<QQqB")    # tpl_id, seq_no, wire_seq(signed), tid_len
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_BH = struct.Struct("<BH")       # kind, name_len
_BHI = struct.Struct("<BHI")     # kind, name_len(0), data_len
_BHB = struct.Struct("<BHB")     # kind, name_len(0), id_len
_NO_TRACE = b"\x00"
_HAS_TRACE = b"\x01"
_EMPTY_U32 = _U32.pack(0)
# Shared immutable-by-convention instance for the default {CPU: 1} demand
# (the worker only READS spec.resources).
_ONE_CPU = Resources(cpu=1.0, tpu=0.0, memory=0.0, custom={})


def pack_desc(tpl_id: int, seq_no: int, wire_seq: int, tid: bytes,
              trace_blob: bytes | None, args, kwargs) -> bytes:
    """Flat binary descriptor for one task (layout: taskrpc.cc
    tpt_send_specs).  args/kwargs hold ValueArg | RefArg."""
    parts = [_HDR.pack(tpl_id, seq_no, wire_seq, len(tid)), tid]
    ap = parts.append
    if trace_blob:
        ap(_HAS_TRACE)
        ap(_U32.pack(len(trace_blob)))
        ap(trace_blob)
    else:
        ap(_NO_TRACE)
    ap(_U16.pack(len(args) + len(kwargs)))
    for a in args:
        data = getattr(a, "data", None)
        if data is not None:                       # ValueArg
            ap(_BHI.pack(0, 0, len(data)))
            ap(data)
            meta = a.metadata
            if meta:
                ap(_U32.pack(len(meta)))
                ap(meta)
            else:
                ap(_EMPTY_U32)
        else:                                      # RefArg
            ap(_BHB.pack(1, 0, len(a.id_binary)))
            ap(a.id_binary)
            owner = a.owner_address.encode()
            ap(_U16.pack(len(owner)))
            ap(owner)
    for k, a in kwargs.items():
        kb = k.encode()
        data = getattr(a, "data", None)
        if data is not None:
            ap(_BH.pack(0, len(kb)))
            ap(kb)
            ap(_U32.pack(len(data)))
            ap(data)
            meta = a.metadata or b""
            ap(_U32.pack(len(meta)))
            ap(meta)
        else:
            ap(_BH.pack(1, len(kb)))
            ap(kb)
            ap(struct.pack("<B", len(a.id_binary)))
            ap(a.id_binary)
            owner = a.owner_address.encode()
            ap(_U16.pack(len(owner)))
            ap(owner)
    return b"".join(parts)


def build_template(*, job_id: bytes, name: str, fn_key: str,
                   num_returns: int, resources, max_retries: int,
                   retry_exceptions: bool, owner_address: str,
                   scheduling_strategy: str = "DEFAULT",
                   runtime_env: dict | None = None,
                   actor_id: bytes = b"", method_name: str = "",
                   max_concurrency: int = 0) -> bytes:
    """Serialize the constant fields of a TaskSpecP (everything but
    task_id/args/kwargs/seq/trace, which the native codec appends)."""
    m = pb.TaskSpecP(
        job_id=job_id,
        name=name,
        fn_key=fn_key,
        num_returns=num_returns,
        max_retries=max_retries,
        retry_exceptions=retry_exceptions,
        owner_address=owner_address,
        scheduling_strategy=scheduling_strategy or "DEFAULT",
        runtime_env_json=(json.dumps(runtime_env, sort_keys=True)
                          if runtime_env else ""),
        actor_id=actor_id,
        method_name=method_name,
        max_concurrency=max_concurrency,
    )
    for k, v in resources.to_dict().items():
        m.resources.amounts[k] = v
    return m.SerializeToString()


# ---------------------------------------------------------------------------
# Full-spec encode (slow/coroutine path) and worker-side decode
# ---------------------------------------------------------------------------


def push_request_to_wire(spec, caller_id: bytes, wire_seq: int) -> bytes:
    """Encode a complete PushTaskRequest (cold path: retries, exotic
    scheduling, actor discovery) — full fidelity via convert.py."""
    m = pb.PushTaskRequest()
    m.spec.CopyFrom(taskspec_to_proto(spec))
    if spec.trace_ctx is not None:
        m.spec.trace_ctx = pickle.dumps(spec.trace_ctx, protocol=5)
    m.caller_id = caller_id
    m.wire_seq = wire_seq
    return m.SerializeToString()


def push_request_from_wire(payload: bytes):
    """Worker-side decode: wire bytes -> (TaskSpec, caller_id, wire_seq).

    Hand-tuned: this runs once per received task on the execution
    thread, so it reads each proto field exactly once and constructs the
    dataclass through __new__ (upb field reads dominate; the general
    converter costs ~4x this)."""
    m = pb.PushTaskRequest.FromString(payload)
    s = m.spec
    spec = TaskSpec.__new__(TaskSpec)
    d = spec.__dict__
    d["task_id"] = TaskID(s.task_id)
    d["job_id"] = JobID(s.job_id)
    d["name"] = s.name
    d["fn_key"] = s.fn_key
    d["args"] = [_arg_fast(a) for a in s.args]
    kw = s.kwargs
    d["kwargs"] = ({k: _arg_fast(v) for k, v in kw.items()} if kw else {})
    d["num_returns"] = s.num_returns or 1
    amounts = s.resources.amounts
    if len(amounts) == 1 and amounts.get("CPU") == 1.0:
        d["resources"] = _ONE_CPU    # the overwhelmingly common demand
    else:
        amounts = dict(amounts)
        d["resources"] = Resources(
            cpu=amounts.pop("CPU", 0.0), tpu=amounts.pop("TPU", 0.0),
            memory=amounts.pop("memory", 0.0), custom=amounts)
    d["max_retries"] = s.max_retries
    d["retry_exceptions"] = s.retry_exceptions
    d["owner_address"] = s.owner_address
    aid = s.actor_id
    d["actor_id"] = ActorID(aid) if aid else None
    d["actor_creation"] = s.actor_creation
    d["method_name"] = s.method_name
    d["seq_no"] = s.seq_no
    d["max_concurrency"] = s.max_concurrency
    pg = s.placement_group_id
    d["placement_group"] = PlacementGroupID(pg) if pg else None
    d["bundle_index"] = s.bundle_index
    na = s.node_affinity
    d["node_affinity"] = NodeID(na) if na else None
    d["node_affinity_soft"] = s.node_affinity_soft
    d["scheduling_strategy"] = s.scheduling_strategy or "DEFAULT"
    rj = s.runtime_env_json
    d["runtime_env"] = json.loads(rj) if rj else {}
    tc = s.trace_ctx
    d["trace_ctx"] = pickle.loads(tc) if tc else None
    return spec, m.caller_id, m.wire_seq


def _arg_fast(a):
    i = a.id
    if i:
        return RefArg(i, a.owner_address)
    v = a.value
    return ValueArg(v.data, v.metadata)


# ---------------------------------------------------------------------------
# Replies
# ---------------------------------------------------------------------------


def reply_to_wire(reply: dict) -> bytes:
    """Runtime reply dict -> PushTaskReply bytes.  Same-language error
    fidelity rides error_blob (pickled exception); cross-language peers
    read error_type/error_message."""
    m = pb.PushTaskReply()
    err = reply.get("error")
    if err is not None:
        m.error_type = type(err).__name__
        m.error_message = str(err)[:4096]
        try:
            m.error_blob = pickle.dumps(err, protocol=5)
        except Exception:
            from ray_tpu.exceptions import TaskError
            m.error_blob = pickle.dumps(
                TaskError("reply", f"unpicklable error: {err!r}", None),
                protocol=5)
        return m.SerializeToString()
    for kind, payload, meta in reply["returns"]:
        r = m.returns.add()
        if kind == "inline":
            r.inline.data = payload
            if meta:
                r.inline.metadata = meta
            r.inline.codec = "pickle5"
        else:
            r.location = payload
            if meta:
                r.metadata = meta
    return m.SerializeToString()


def reply_from_wire(data: bytes) -> dict:
    m = pb.PushTaskReply.FromString(data)
    if m.error_blob or m.error_type:
        if m.error_blob:
            try:
                err = pickle.loads(m.error_blob)
            except Exception:
                err = None
        else:
            err = None
        if err is None:
            from ray_tpu.exceptions import TaskError
            err = TaskError(m.error_type or "remote",
                            m.error_message, None)
        return {"returns": [], "error": err}
    returns = []
    for r in m.returns:
        if r.WhichOneof("value") == "inline":
            returns.append(("inline", r.inline.data, r.inline.metadata))
        else:
            returns.append(("location", r.location, r.metadata))
    return {"returns": returns, "error": None}
