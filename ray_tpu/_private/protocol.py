"""Wire-level task/actor specs and options.

Reference parity: src/ray/common/task/task_spec.h + python/ray/_private/
ray_option_utils.py (option surface) — trimmed to the fields the runtime
uses today; every field name matches the reference concept it mirrors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID, TaskID

# Results smaller than this return inline in the PushTask reply and live in
# the owner's memory store (reference: task returns "in plasma" vs "direct").
INLINE_LIMIT = 100 * 1024


@dataclass
class Resources:
    """Logical resource demand. TPU is first-class (the reference only knows
    GPU; accelerators live in python/ray/util/accelerators/accelerators.py)."""

    cpu: float = 1.0
    tpu: float = 0.0
    memory: float = 0.0
    custom: dict = field(default_factory=dict)

    def __reduce__(self):
        return (Resources, (self.cpu, self.tpu, self.memory, self.custom))

    def to_dict(self) -> dict:
        d = dict(self.custom)
        if self.cpu:
            d["CPU"] = self.cpu
        if self.tpu:
            d["TPU"] = self.tpu
        if self.memory:
            d["memory"] = self.memory
        return d

    @classmethod
    def from_options(cls, opts: dict, default_cpu: float = 1.0) -> "Resources":
        # NB: options default to None (unset), which must mean "default", not
        # zero — otherwise every task demands nothing and admission control
        # stops gating concurrency.
        cpu = opts.get("num_cpus")
        tpu = opts.get("num_tpus")
        mem = opts.get("memory")
        custom = dict(opts.get("resources") or {})
        # accelerator_type targets nodes advertising that hardware
        # (reference: ray_option_utils.py accelerator_type:74 — adds a
        # fractional accelerator_type:<T> resource demand).
        acc = opts.get("accelerator_type")
        if acc:
            custom.setdefault(f"accelerator_type:{acc}", 0.001)
        return cls(
            cpu=default_cpu if cpu is None else float(cpu),
            tpu=0.0 if tpu is None else float(tpu),
            memory=0.0 if mem is None else float(mem),
            custom=custom,
        )


# An argument is either an inline serialized value or an object reference.
@dataclass
class ValueArg:
    data: bytes
    metadata: bytes

    def __reduce__(self):  # tuple-based: ~2x faster than dataclass default
        return (ValueArg, (self.data, self.metadata))


@dataclass
class RefArg:
    id_binary: bytes
    owner_address: str

    def __reduce__(self):
        return (RefArg, (self.id_binary, self.owner_address))


def _mk_taskspec(*fields) -> "TaskSpec":
    """Positional reconstructor for TaskSpec.__reduce__ (pickling a spec
    sits on the per-task hot path on both sides of the wire; a tuple
    avoids the dataclass default's per-field name dict)."""
    s = TaskSpec.__new__(TaskSpec)
    (s.task_id, s.job_id, s.name, s.fn_key, s.args, s.kwargs,
     s.num_returns, s.resources, s.max_retries, s.retry_exceptions,
     s.owner_address, s.actor_id, s.actor_creation, s.method_name,
     s.seq_no, s.max_concurrency, s.placement_group, s.bundle_index,
     s.node_affinity, s.node_affinity_soft, s.scheduling_strategy,
     s.runtime_env, s.trace_ctx) = fields
    return s


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    name: str                     # human-readable function/method name
    fn_key: str                   # GCS KV key of the pickled function/class
    args: list                    # list[ValueArg | RefArg]
    kwargs: dict                  # name -> ValueArg | RefArg
    num_returns: int = 1
    resources: Resources = field(default_factory=Resources)
    max_retries: int = 3
    retry_exceptions: bool = False
    owner_address: str = ""       # RPC address of the submitting worker
    # Actor fields
    actor_id: Optional[ActorID] = None       # set for actor method calls
    actor_creation: bool = False             # this task constructs an actor
    method_name: str = ""
    seq_no: int = 0               # per-handle ordering for actor tasks
    # Execution concurrency for the created actor; 0 = unset, so the worker
    # can apply per-mode defaults (async actors: 1000, sync: 1).  Reference:
    # core_worker/transport/concurrency_group_manager.h + thread_pool.h.
    max_concurrency: int = 0
    # Scheduling hints
    placement_group: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    node_affinity: Optional[NodeID] = None
    node_affinity_soft: bool = True
    scheduling_strategy: str = "DEFAULT"     # DEFAULT | SPREAD
    runtime_env: dict = field(default_factory=dict)
    # Propagated trace context (trace_id, span_id) — injected at submit,
    # extracted at execute (reference: tracing_helper.py:87).
    trace_ctx: Optional[tuple] = None

    def __reduce__(self):
        return (_mk_taskspec, (
            self.task_id, self.job_id, self.name, self.fn_key, self.args,
            self.kwargs, self.num_returns, self.resources,
            self.max_retries, self.retry_exceptions, self.owner_address,
            self.actor_id, self.actor_creation, self.method_name,
            self.seq_no, self.max_concurrency, self.placement_group,
            self.bundle_index, self.node_affinity,
            self.node_affinity_soft, self.scheduling_strategy,
            self.runtime_env, self.trace_ctx))


@dataclass
class ActorInfo:
    """GCS actor-table record (reference: gcs_actor_manager.h state machine)."""

    actor_id: ActorID
    name: str = ""
    namespace: str = "default"
    class_name: str = ""
    state: str = "PENDING"  # PENDING/ALIVE/RESTARTING/DEAD
    address: str = ""       # worker RPC address when ALIVE
    native_port: int = 0    # worker's framed-TCP task plane, 0 = none
    node_id: Optional[NodeID] = None
    owner_address: str = ""
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: str = ""
    lifetime_detached: bool = False
    creation_spec: Optional[TaskSpec] = None
    resources: Resources = field(default_factory=Resources)
    version: int = 0        # bumped on every state change (client cache inval)


@dataclass
class PlacementGroupInfo:
    """GCS placement-group table record.

    Reference parity: src/ray/gcs/gcs_server/gcs_placement_group_manager.h
    (lifecycle) + gcs_placement_group_scheduler.h (bundle 2PC against
    raylets, node_manager.proto:378 Prepare/CommitBundleResources).
    """

    pg_id: PlacementGroupID
    bundles: list                 # list[dict] resource demand per bundle
    strategy: str = "PACK"        # PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
    name: str = ""
    state: str = "PENDING"        # PENDING/CREATED/RESCHEDULING/REMOVED
    # Per-bundle placement, filled when scheduled (None = unplaced).
    bundle_nodes: list = field(default_factory=list)      # list[NodeID|None]
    bundle_addresses: list = field(default_factory=list)  # list[str]
    creator_job: int = 0
    lifetime_detached: bool = False
    version: int = 0


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str            # hostd RPC address
    store_path: str         # shm segment path (same-host attach)
    hostname: str = ""
    resources_total: dict = field(default_factory=dict)
    resources_available: dict = field(default_factory=dict)
    alive: bool = True
    is_head: bool = False
    # Node incarnation: bumped by the GCS when it fences a node that
    # re-registers after being declared dead (its actors already failed
    # over).  The actor-path incarnation guards key on addresses; this is
    # the node-level analogue, so a healed-but-stale gang can never
    # double-apply an update.  getattr-defensive readers tolerate 0 on
    # records restored from pre-incarnation sqlite tables.
    incarnation: int = 0


def option_defaults(for_actor: bool = False) -> dict:
    """The @remote option surface (reference: _private/ray_option_utils.py)."""
    common = {
        "num_cpus": None, "num_tpus": None, "memory": None, "resources": None,
        "accelerator_type": None,
        "runtime_env": None, "scheduling_strategy": None, "name": None,
        "placement_group": None, "placement_group_bundle_index": -1,
        "_node_id": None,
    }
    if for_actor:
        common.update({
            "max_restarts": 0, "max_task_retries": 0, "lifetime": None,
            "namespace": None, "max_concurrency": None, "get_if_exists": False,
        })
    else:
        common.update({
            "num_returns": 1, "max_retries": 3, "retry_exceptions": False,
        })
    return common


def validate_options(opts: dict, for_actor: bool) -> dict:
    allowed = option_defaults(for_actor)
    merged = dict(allowed)
    for k, v in opts.items():
        if k not in allowed:
            kind = "actor" if for_actor else "task"
            raise ValueError(f"invalid {kind} option {k!r}; allowed: {sorted(allowed)}")
        merged[k] = v
    return merged


Any  # keep typing import alive for doc tooling
