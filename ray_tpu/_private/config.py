"""Central config registry: typed flags, env-overridable.

Reference parity: src/ray/common/ray_config_def.h (~700 RAY_CONFIG(type,
name, default) entries overridable via RAY_<name> env vars or the
_system_config dict at init, mirrored through includes/ray_config.pxi).
Here every knob is declared once, reads `RAY_TPU_<NAME>` from the
environment, and can be overridden per-process via
`ray_tpu.init(_system_config={...})`.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict


class _Flag:
    __slots__ = ("name", "default", "cast", "doc")

    def __init__(self, name: str, default, cast: Callable, doc: str):
        self.name = name
        self.default = default
        self.cast = cast
        self.doc = doc


def _bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() not in ("0", "false", "no", "off", "")


class RayTpuConfig:
    """Singleton registry; access flags as attributes."""

    _FLAGS: Dict[str, _Flag] = {}

    @classmethod
    def _define(cls, name: str, default, cast, doc: str):
        cls._FLAGS[name] = _Flag(name, default, cast, doc)

    def __init__(self):
        self._overrides: Dict[str, Any] = {}
        self._cache: Dict[str, Any] = {}

    def apply_system_config(self, overrides: Dict[str, Any] | None) -> None:
        """ray_tpu.init(_system_config={...}) hook."""
        for k, v in (overrides or {}).items():
            if k not in self._FLAGS:
                raise ValueError(f"unknown config flag {k!r}; known: "
                                 f"{sorted(self._FLAGS)}")
            self._overrides[k] = self._FLAGS[k].cast(v)
        self._cache.clear()

    def invalidate_cache(self) -> None:
        """Call after mutating RAY_TPU_* env vars in-process (tests do)."""
        self._cache.clear()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # Resolved values are cached: flag reads sit on per-task hot paths
        # (lease pump, submit), and an os.environ hit per read is ~7us.
        cached = self._cache.get(name, self)
        if cached is not self:
            return cached
        flag = self._FLAGS.get(name)
        if flag is None:
            raise AttributeError(name)
        if name in self._overrides:
            value = self._overrides[name]
        else:
            env = os.environ.get(f"RAY_TPU_{name.upper()}")
            value = flag.cast(env) if env is not None else flag.default
        self._cache[name] = value
        return value

    def dump(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in sorted(self._FLAGS)}


_D = RayTpuConfig._define
# -- core runtime ----------------------------------------------------------
_D("object_store_memory", 256 << 20, int,
   "default per-node shared-memory store capacity (bytes)")
_D("inline_object_limit", 100 * 1024, int,
   "returns/args below this size travel inline instead of via the store")
_D("lease_idle_ttl_s", 1.0, float,
   "held worker leases idle past this return to the daemon")
_D("max_pending_lease_requests", 16, int,
   "in-flight LeaseWorker RPCs per scheduling key")
_D("lease_pipeline_depth", 8, int,
   "tasks in flight per held worker lease (receiver queues them; "
   "reference: OnWorkerIdle pushes all queued tasks onto a lease)")
_D("task_max_retries", 3, int, "default task retry budget")
_D("worker_idle_ttl_s", 60.0, float,
   "idle pooled workers are reaped after this")
_D("max_workers_per_node", 0, int,
   "worker-pool cap per node; 0 = max(8, 4x CPUs)")
_D("max_startup_concurrency", 0, int,
   "concurrent worker spawns per node; 0 = max(4, host core count)")
_D("worker_zygote", True, _bool,
   "fork non-TPU workers from a pre-imported template process "
   "(worker_zygote.py) instead of cold-spawning an interpreter")
_D("native_task_transport", True, _bool,
   "push tasks over the native framed-TCP plane (taskrpc.cc) instead of "
   "the Python RPC layer")
_D("heartbeat_interval_s", 0.5, float, "hostd -> GCS heartbeat period")
_D("gcs_flush_interval_ms", 200.0, float,
   "GCS persistence debounce: a burst of table mutations becomes one "
   "sqlite executemany transaction at most this often")
_D("node_death_timeout_s", 5.0, float,
   "missed-heartbeat window before a node is declared dead")
# -- spilling --------------------------------------------------------------
_D("spill_enabled", True, _bool, "spill to disk instead of LRU eviction")
_D("spill_high_watermark", 0.8, float, "store fraction that starts a sweep")
_D("spill_low_watermark", 0.5, float, "sweep target store fraction")
# -- memory monitor --------------------------------------------------------
_D("memory_monitor_enabled", True, _bool,
   "kill workers when node memory nears exhaustion")
_D("memory_usage_threshold", 0.95, float,
   "node memory fraction that triggers OOM worker killing")
_D("memory_monitor_interval_s", 1.0, float, "memory check period")
# -- serve -----------------------------------------------------------------
_D("serve_controller_threads", 64, int,
   "controller thread pool (long-polls + control loop)")
_D("serve_backpressure_timeout_s", 60.0, float,
   "how long a handle waits for a replica under its "
   "max_concurrent_queries cap before raising TimeoutError")
_D("serve_drain_deadline_s", 30.0, float,
   "how long a DRAINING replica may finish its in-flight requests "
   "before the controller force-kills it")
_D("serve_queue_length", 128, int,
   "default per-deployment admission queue bound: callers waiting for a "
   "replica slot beyond this fast-fail with ServeOverloadedError "
   "(0 = unbounded, legacy backpressure-wait behavior)")
_D("serve_retry_after_hint_s", 1.0, float,
   "retry-after hint carried by ServeOverloadedError when a request "
   "is shed at the admission queue")
_D("serve_request_deadline_s", 0.0, float,
   "default end-to-end deadline for every serve request (admission + "
   "execution + retries); 0 = none.  Per-call override: "
   "handle.options(timeout_s=...)")
_D("serve_failover_attempts", 2, int,
   "max mid-stream failover resubmissions per streaming request")
_D("spec_k", 4, int,
   "default speculative draft length when an engine/deployment enables "
   "speculative decoding: up to this many draft tokens ride each verify "
   "step (the verify dispatch shape is spec_k+1)")
_D("spec_adaptive", True, _bool,
   "adapt each lane's draft length to its measured acceptance: grow on "
   "full acceptance, back off on rejection, so incompressible streams "
   "stop paying rejected verify FLOPs")
# -- disaggregated serving / KV tier ---------------------------------------
_D("serve_prefix_routing", False, _bool,
   "prefix-cache-aware replica routing: the handle scrapes a compact "
   "prefix-index summary from each LLM replica and routes a request to "
   "the replica holding its longest cached prefix chain, falling back "
   "to power-of-two-choices on ties or stale summaries.  Off by "
   "default: non-LLM deployments have no summary to scrape")
_D("serve_prefix_scrape_s", 1.0, float,
   "period of the router's prefix-summary scrape thread")
_D("serve_prefix_staleness_s", 5.0, float,
   "summaries older than this never attract traffic (dead or "
   "redeployed replicas age out of prefix scoring within one bound)")
_D("serve_prefix_summary_size", 256, int,
   "max chain hashes a replica exports per prefix summary (newest "
   "sealed blocks win — bounds scrape payload size)")
_D("kv_tier", False, _bool,
   "tiered KV cache: refcount-0 sealed blocks spill to host memory "
   "and then the object store / disk instead of being destroyed; the "
   "prefix index keeps a SPILLED state and match/adopt restores "
   "spilled chains on hit")
_D("kv_tier_host_blocks", 256, int,
   "host-memory tier capacity in KV blocks (LRU beyond this "
   "overflows to the store tier)")
_D("kv_tier_store_blocks", 1024, int,
   "object-store/disk tier capacity in KV blocks (LRU beyond this "
   "is dropped for real); 0 disables the second tier")
# -- train fault tolerance -------------------------------------------------
_D("train_hang_timeout_s", 60.0, float,
   "gang declared hung when NO worker makes observable progress (a "
   "consumed report or an advanced step beacon) for this long; the "
   "watchdog then collects per-rank stacks and fails the gang instead "
   "of waiting in a collective forever.  Must exceed the slowest "
   "legitimate train step.")
_D("train_beacon_poll_s", 5.0, float,
   "how often the driver-side watchdog polls worker step beacons while "
   "blocked waiting on gang reports")
_D("train_elastic_timeout_s", 120.0, float,
   "overall deadline for an elastic restart to form SOME gang between "
   "min_workers and num_workers before the restart fails")
_D("train_pg_timeout_s", 15.0, float,
   "placement-group reservation wait per elastic gang-size attempt "
   "(the non-elastic path keeps its legacy 120s wait)")
_D("train_resize_check_interval_s", 5.0, float,
   "how often a resized-down gang probes the cluster for returned "
   "capacity (resize-up happens at the next step boundary after a "
   "successful probe)")
_D("worker_sigterm_grace_s", 3.0, float,
   "bounded SIGTERM -> wait -> SIGKILL escalation window: how long a "
   "terminated worker may finish its in-flight task before the kill "
   "(hostd child teardown and the worker's own SIGTERM handler)")
# -- ingest / device feed --------------------------------------------------
_D("ingest_queue_depth", 2, int,
   "bounded handoff queue between the background batch producer and the "
   "training thread (batches buffered ahead of the consumer)")
_D("ingest_prefetch_blocks", 4, int,
   "block refs the ingest path touches ahead of the blocking fetch")
_D("ingest_device_buffers", 2, int,
   "device batches kept in flight by iter_device_batches: while the "
   "jitted step consumes batch k, batch k+1 is already being device_put")
_D("ingest_work_stealing", False, _bool,
   "trainer dataset shards lease blocks from a SplitCoordinator instead "
   "of static per-worker lists — a straggler no longer strands its "
   "shard.  Off by default: the static split is deterministic "
   "(token-exact elastic restores)")
_D("ingest_lease_timeout_s", 30.0, float,
   "a work-stealing split re-queues a worker's outstanding block leases "
   "once the worker has been silent this long AND the fresh pool is "
   "exhausted (crash recovery; mark_dead re-queues immediately)")
# -- observability / flight recorder ---------------------------------------
_D("events", True, _bool,
   "flight recorder master switch: every plane appends structured "
   "decision events to a per-process ring buffer (util/events.py), "
   "dumped on crash and scrapeable via CollectEvents.  RAY_TPU_EVENTS=0 "
   "reduces record() to a single global read")
_D("events_ring_size", 4096, int,
   "flight-recorder ring capacity (events per process); overflow "
   "overwrites oldest")
_D("flightrec_dir", "", str,
   "directory for crash dumps (flightrec-<pid>-<incarnation>.jsonl); "
   "hostd points workers at <session>/logs via RAY_TPU_FLIGHTREC_DIR, "
   "empty = /tmp/ray_tpu/flightrec")
_D("telemetry_port", 0, int,
   "base port for the pull telemetry HTTP endpoints (/metrics /events "
   "/healthz) served by hostd and the driver; 0 = ephemeral (the bound "
   "port is announced as a proc/telemetry_listen event).  The server "
   "only starts when the flight recorder is enabled; -1 disables it "
   "outright")
_D("telemetry_host", "127.0.0.1", str,
   "bind address for the telemetry HTTP endpoints; set 0.0.0.0 to "
   "expose scrapes off-host")
# -- scheduling ------------------------------------------------------------
_D("scheduler_spread_threshold", 0.5, float,
   "hybrid policy: pack until this utilization, then best-node")
_D("sched_batch_max", 8, int,
   "worker grants requested per LeaseWorker RPC: a deep same-key queue "
   "asks the hostd for up to this many workers in ONE round trip "
   "instead of one RPC per lease (the hostd grants what it can and the "
   "driver re-pumps for the rest); 1 = legacy single-grant leasing")
_D("sched_batch_wait_ms", 0.0, float,
   "optional submit-side coalescing window: the fast-path drain waits "
   "up to this long for more same-burst submissions before flushing "
   "its per-worker dispatch batches (0 = flush at the end of the "
   "current loop tick, the latency-neutral default)")
_D("zygote_spawn_parallelism", 8, int,
   "forks per zygote wakeup: concurrent spawn requests coalesce into "
   "one batched fork request of up to this many children (and the "
   "hostd pre-warm pool seeds at most this many workers per tick)")
_D("worker_prewarm", True, _bool,
   "hostd pre-warms idle workers sized by recent lease demand while "
   "the zygote is serving, so storms stop paying cold-spawn per lease")
# -- rpc retry -------------------------------------------------------------
_D("rpc_max_retries", 4, int,
   "transient-failure (UNAVAILABLE/disconnect) retries per RpcClient.call; "
   "0 disables retrying")
_D("rpc_retry_base_ms", 50.0, float,
   "first retry backoff; doubles per attempt with +/-50% jitter")
_D("rpc_retry_max_ms", 2000.0, float, "backoff ceiling per retry sleep")
# -- GCS fault tolerance ---------------------------------------------------
_D("gcs_supervise", False, _bool,
   "the launcher supervises the GCS child: on an unexpected death it "
   "respawns `python -m ray_tpu._private.gcs` at the SAME address from "
   "the same sqlite persistence path, so clients reconnect without "
   "re-resolving anything.  Implies persistence (a gcs.sqlite under the "
   "session dir) when RAY_TPU_GCS_PERSIST is unset")
_D("gcs_supervisor_restarts", 10, int,
   "supervised-GCS respawn budget per cluster lifetime; past it the "
   "supervisor gives up and the cluster degrades to today's "
   "head-is-gone behavior")
_D("gcs_outage_deadline_s", 30.0, float,
   "GcsClient ride-through window: control-plane calls buffer-and-retry "
   "transport failures against the (restarting) GCS for up to this long "
   "before surfacing the error.  The data plane is peer-to-peer and "
   "never waits on this")
_D("gcs_silent_window_s", 90.0, float,
   "hostd suicide window: heartbeat loop exits the daemon after the GCS "
   "has been unreachable this long — UNLESS gcs_supervise is on, in "
   "which case the hostd rides the outage out and re-registers on "
   "reconnect instead of orphaning its workers")
# -- fault injection (chaos) ----------------------------------------------
# Deterministic seeded chaos: see _private/fault_injection.py.  All
# probabilities are per-event in [0,1]; flags propagate to daemons and
# workers through the RAY_TPU_* env export in api.init.
_D("chaos_enabled", False, _bool,
   "master switch for the fault-injection layer")
_D("chaos_seed", 0, int,
   "seed for the deterministic fault schedule (same seed => same faults)")
_D("chaos_max_faults", 0, int,
   "total faults to inject before going quiet; 0 = unlimited")
_D("chaos_rpc_drop", 0.0, float,
   "probability an outbound RPC attempt fails with ChaosInjectedError")
_D("chaos_rpc_delay_p", 0.0, float,
   "probability an outbound RPC attempt is delayed")
_D("chaos_rpc_delay_ms", 100.0, float, "injected RPC delay duration")
_D("chaos_rpc_disconnect", 0.0, float,
   "probability an outbound RPC attempt tears down its channel first")
_D("chaos_native_drop", 0.0, float,
   "probability a native-transport task push is dropped")
_D("chaos_object_fetch_drop", 0.0, float,
   "probability an object-transfer fetch reports the copy missing")
_D("chaos_kill_worker", 0.0, float,
   "probability a worker kills itself before executing a task")
_D("chaos_kill_worker_salts", "", str,
   "scripted kills: csv of worker spawn ordinals that self-kill (see "
   "fault_injection.ChaosController.kill_worker)")
_D("chaos_kill_worker_at", 0, int,
   "task-execution index at which a scripted worker kill fires")
_D("chaos_kill_hostd", 0.0, float,
   "probability hostd kills itself at a heartbeat tick")
_D("chaos_kill_hostd_salts", "", str,
   "scripted hostd kills: csv of hostd spawn ordinals ('h1', 'h2', ... "
   "as stamped by node.start_hostd; or '*' for any non-head hostd) that "
   "die at their chaos_kill_hostd_at-th heartbeat tick (see "
   "fault_injection.ChaosController.kill_hostd)")
_D("chaos_kill_hostd_at", 0, int,
   "heartbeat tick ordinal at which the scripted hostd kill fires")
_D("chaos_ckpt_kill", 0.0, float,
   "probability the checkpoint writer kills its process right before the "
   "COMMIT rename (data fully written, directory left torn)")
_D("chaos_ckpt_kill_salts", "", str,
   "scripted mid-save kills: csv of worker spawn ordinals whose "
   "checkpoint writer dies (see fault_injection.kill_ckpt_commit)")
_D("chaos_ckpt_kill_at", 0, int,
   "save ordinal at which the scripted mid-save kill fires")
_D("chaos_kill_replica", 0.0, float,
   "probability a serve replica kills its process at a serve-plane "
   "event (request dispatch or stream-chunk pull)")
_D("chaos_kill_replica_salts", "", str,
   "scripted replica kills: csv of worker spawn ordinals (or '*' for "
   "any serve replica process) that die at their chaos_kill_replica_at-"
   "th serve-plane event (see fault_injection.kill_replica)")
_D("chaos_kill_replica_at", 0, int,
   "serve-plane event index at which the scripted replica kill fires")
_D("chaos_preempt", 0.0, float,
   "probability a hostd receives a preemption notice at a heartbeat "
   "tick (simulated TPU maintenance event: SIGTERM after a grace "
   "window)")
_D("chaos_preempt_at", -1, int,
   "scripted preemption: heartbeat tick ordinal at which the notice "
   "fires on every hostd matching chaos_preempt_target (-1 = disabled)")
_D("chaos_preempt_target", "any", str,
   "which hostds a scripted preemption hits: 'any', 'head', or "
   "'nonhead'.  A preempted head degrades to killing only its workers "
   "(slice loss) instead of exiting, so a colocated GCS survives.")
_D("chaos_preempt_grace_s", 5.0, float,
   "grace window between the injected preemption notice and the kill")
_D("chaos_stall_worker", 0.0, float,
   "probability a train worker stalls at a step boundary (hang chaos "
   "for the train watchdog)")
_D("chaos_stall_worker_salts", "", str,
   "scripted stalls: csv of worker spawn ordinals that stall at their "
   "chaos_stall_at-th session.report (see "
   "fault_injection.stall_train_step)")
_D("chaos_stall_at", 0, int,
   "report ordinal at which the scripted train stall fires")
_D("chaos_stall_s", 3600.0, float,
   "how long an injected train stall sleeps (interruptible; default "
   "is effectively forever relative to train_hang_timeout_s)")
_D("chaos_kill_gcs_at", -1, int,
   "scripted GCS kill: the GCS process os._exit(1)s right before "
   "serving its N-th control-plane request (-1 = disabled).  Which "
   "request lands on ordinal N is scenario-determined: a heartbeat, a "
   "PG schedule, a KV put — the supervised restart must absorb any of "
   "them (see fault_injection.ChaosController.kill_gcs)")
_D("chaos_kill_gcs_salts", "gcs0", str,
   "which GCS incarnations a scripted kill arms on: csv of process "
   "salts ('gcs0' is the first boot, 'gcs1' the first supervised "
   "respawn, ...; '*' = every incarnation).  The default arms only the "
   "first boot so a supervised respawn converges instead of dying at "
   "the same ordinal forever")
_D("chaos_kill_gcs_flush_at", -1, int,
   "scripted mid-flush GCS kill: os._exit(1) INSIDE the sqlite "
   "write_rows transaction of the N-th persistence flush, after the "
   "executemany but before commit (-1 = disabled).  Proves the "
   "coalesced-write path is crash-atomic: the torn flush must roll "
   "back wholesale on restore")
_D("chaos_partition_links", "", str,
   "scripted sustained network partitions: ';'-separated rules "
   "'src>dst@start+duration', e.g. 'h2>gcs@40+6.0;driver>gcs@0+2'. "
   "src names a process salt ('h2', 'gcs0', 'driver' for the saltless "
   "driver, '*' for any); dst is 'gcs', a literal host:port, or '*'. "
   "The rule blackholes every matching outbound rpc/native send "
   "starting at the src process's start-th call on that link, for "
   "duration seconds, then heals.  Directional — partition asymmetry "
   "is expressed by listing one direction only (see "
   "fault_injection.ChaosController.link_fault)")


GLOBAL_CONFIG = RayTpuConfig()


def get_config() -> RayTpuConfig:
    return GLOBAL_CONFIG
