"""Native object-transfer data plane: ctypes bindings for objtransfer.cc.

Reference parity: src/ray/object_manager/ (chunked push/pull between
Plasma stores).  The control decisions (which node, spill restore,
fallbacks) stay in the Python daemons; payload bytes move shm-to-shm over
a raw TCP connection with no Python in the data path.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, Optional

from ray_tpu._private.ids import ObjectID

_OK = 0
_EXISTS = -1
_NOT_FOUND = -2
_OOM = -3
_SYS = -6
_PROTO = -7

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            from ray_tpu import _native
            lib = ctypes.CDLL(_native.lib_path("tpuxfer"))
            lib.tpot_server_start.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_void_p)]
            lib.tpot_server_stop.argtypes = [ctypes.c_void_p]
            lib.tpot_server_stop.restype = None
            lib.tpot_attach.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_void_p)]
            lib.tpot_detach.argtypes = [ctypes.c_void_p]
            lib.tpot_detach.restype = None
            lib.tpot_fetch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_char_p]
            _lib = lib
    return _lib


class TransferServer:
    """Serves the local store's sealed objects over TCP (one per hostd)."""

    def __init__(self, store_path: str, port: int = 0):
        lib = _load()
        out_port = ctypes.c_int()
        srv = ctypes.c_void_p()
        rc = lib.tpot_server_start(store_path.encode(), port,
                                   ctypes.byref(out_port), ctypes.byref(srv))
        if rc != _OK:
            raise RuntimeError(f"transfer server start failed (rc={rc})")
        self.port = out_port.value
        self._srv = srv
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            _load().tpot_server_stop(self._srv)


# One fetch handle per (process, store path); attaching per fetch would
# burn a client slot + mmap each time.
_clients: Dict[str, ctypes.c_void_p] = {}
_clients_lock = threading.Lock()


def _client(store_path: str) -> ctypes.c_void_p:
    with _clients_lock:
        h = _clients.get(store_path)
        if h is None:
            lib = _load()
            h = ctypes.c_void_p()
            rc = lib.tpot_attach(store_path.encode(), ctypes.byref(h))
            if rc != _OK:
                raise RuntimeError(
                    f"transfer client attach failed (rc={rc})")
            _clients[store_path] = h
        return h


def fetch(store_path: str, host: str, port: int, oid: ObjectID) -> bool:
    """Pull `oid` from host:port into the local store (sealed).

    Returns True when the object is now locally available (fetched, or
    already present), False when the remote does not have it.  Raises on
    transport/allocation failures.  BLOCKING — call from an executor
    thread, never the event loop.
    """
    from ray_tpu._private.fault_injection import get_chaos
    chaos = get_chaos()
    if chaos is not None and chaos.object_fetch_drop():
        # Injected lost copy: report not-found so the caller's location
        # failover (and ultimately lineage reconstruction) takes over.
        return False
    rc = _load().tpot_fetch(_client(store_path), host.encode(), port,
                            oid.binary())
    if rc in (_OK, _EXISTS):
        return True
    if rc == _NOT_FOUND:
        return False
    if rc == _OOM:
        from ray_tpu.exceptions import ObjectStoreFullError
        raise ObjectStoreFullError(f"no room to receive {oid}")
    raise RuntimeError(f"native fetch of {oid} from {host}:{port} "
                       f"failed (rc={rc})")
