"""Python binding for the native task-submission transport (taskrpc.cc).

Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
(submitter: pipelined PushTask over leased workers) and
direct_actor_transport.h:50 (receiver-side ordered execution queues).  The
C++ plane owns connections, framing, pipelining, and batched completion
delivery; Python supplies payload bytes (pickled TaskSpec) on one side and
executes user functions on the other.

Submitter: `NativeSubmitter.call(addr, payload)` is awaitable on the core
worker's event loop.  A single poller thread drains completion batches from
C++ and resolves futures with ONE loop wakeup per batch.

Receiver: `NativeReceiver` runs a C++ server plus an executor thread that
pops task batches; each task is handed to a handler callable
(payload) -> bytes | awaitable-scheduler, and the reply streams back
through the C++ writer.
"""

from __future__ import annotations

import ctypes
import logging
import struct
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)

_REC_HDR = struct.Struct("<QQiQ")  # tag, req_id, status, payload_len

TPT_OK = 0
TPT_ECONN = -1
TPT_EBUF = -4  # head record exceeds caller buffer; `used` = needed size


class _Lib:
    """Two views of libtpttask: fast entry points go through PyDLL (GIL
    HELD — they only enqueue + memcpy, and releasing/reacquiring the GIL
    per call costs more than the call under thread contention), while the
    blocking poll/pop go through CDLL (GIL released while waiting)."""

    def __init__(self):
        from ray_tpu import _native
        path = _native.lib_path("tpttask")
        fast = ctypes.PyDLL(path)
        blocking = ctypes.CDLL(path)
        fast.tpt_client_new.argtypes = [ctypes.POINTER(ctypes.c_void_p)]
        fast.tpt_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
        fast.tpt_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_uint64, ctypes.c_char_p,
                                  ctypes.c_uint64]
        fast.tpt_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        blocking.tpt_poll.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_int]
        blocking.tpt_client_close.argtypes = [ctypes.c_void_p]
        fast.tpt_server_new.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_void_p),
                                        ctypes.POINTER(ctypes.c_int)]
        blocking.tpt_server_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_uint64,
                                           ctypes.POINTER(ctypes.c_uint64),
                                           ctypes.c_int]
        fast.tpt_server_reply.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                          ctypes.c_uint64, ctypes.c_char_p,
                                          ctypes.c_uint64]
        blocking.tpt_server_close.argtypes = [ctypes.c_void_p]
        self.tpt_client_new = fast.tpt_client_new
        self.tpt_connect = fast.tpt_connect
        self.tpt_send = fast.tpt_send
        self.tpt_close_conn = fast.tpt_close_conn
        self.tpt_poll = blocking.tpt_poll
        self.tpt_client_close = blocking.tpt_client_close
        self.tpt_server_new = fast.tpt_server_new
        self.tpt_server_pop = blocking.tpt_server_pop
        self.tpt_server_reply = fast.tpt_server_reply
        self.tpt_server_close = blocking.tpt_server_close


def _load():
    return _Lib()


_lib = None
_lib_lock = threading.Lock()


def lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            _lib = _load()
        return _lib


def _unpack_records(buf: bytes, used: int):
    """Yield (tag, req_id, status, payload) records from a packed batch."""
    off = 0
    while off < used:
        tag, req_id, status, plen = _REC_HDR.unpack_from(buf, off)
        off += _REC_HDR.size
        payload = bytes(buf[off:off + plen])
        off += plen
        yield tag, req_id, status, payload


class ConnClosedError(ConnectionError):
    """The worker connection died with this request in flight."""


class NativeSubmitter:
    """Driver/owner-side pipelined task pusher."""

    POLL_BUF = 4 << 20

    def __init__(self, loop):
        self._loop = loop
        self._l = lib()
        h = ctypes.c_void_p()
        rc = self._l.tpt_client_new(ctypes.byref(h))
        if rc != 0:
            raise OSError(f"tpt_client_new failed: {rc}")
        self._h = h
        self._conns: dict[str, int] = {}
        self._futs: dict[int, object] = {}   # req_id -> asyncio future
        self._req = 0
        self._mu = threading.Lock()
        self._closed = False
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True, name="tpt-poll")
        self._poller.start()

    # -- connection management -------------------------------------------

    def connect(self, addr: str) -> int:
        """Idempotent connect; returns the conn tag for `host:port`."""
        with self._mu:
            tag = self._conns.get(addr)
            if tag is not None:
                return tag
        host, port = addr.rsplit(":", 1)
        out = ctypes.c_uint64()
        rc = self._l.tpt_connect(self._h, host.encode(), int(port),
                                 ctypes.byref(out))
        if rc != 0:
            raise ConnectionError(f"native connect to {addr} failed ({rc})")
        with self._mu:
            self._conns[addr] = out.value
        return out.value

    def invalidate(self, addr: str):
        with self._mu:
            tag = self._conns.pop(addr, None)
        if tag is not None:
            self._l.tpt_close_conn(self._h, tag)

    # -- submission -------------------------------------------------------

    def call(self, addr: str, payload: bytes):
        """Schedule a request; returns an asyncio future on the owning
        loop (await it there)."""
        import asyncio
        fut = self._loop.create_future()
        try:
            tag = self.connect(addr)
        except ConnectionError as e:
            fut.set_exception(e)
            return fut
        with self._mu:
            self._req += 1
            req_id = self._req
            self._futs[req_id] = fut
        rc = self._l.tpt_send(self._h, tag, req_id, payload, len(payload))
        if rc != 0:
            with self._mu:
                self._futs.pop(req_id, None)
            self.invalidate(addr)
            fut.set_exception(ConnClosedError(f"send to {addr} failed"))
        return fut

    # -- completion pump --------------------------------------------------

    def _poll_loop(self):
        cap = self.POLL_BUF
        buf = ctypes.create_string_buffer(cap)
        used = ctypes.c_uint64()
        while not self._closed:
            n = self._l.tpt_poll(self._h, buf, cap,
                                 ctypes.byref(used), 200)
            if n == TPT_EBUF:
                # Oversized head record: grow and retry (the bigger
                # buffer sticks, so growth is amortized).
                cap = max(cap * 2, int(used.value))
                buf = ctypes.create_string_buffer(cap)
                continue
            if n <= 0:
                continue
            batch = []
            # string_at copies only the used prefix (buf.raw would copy
            # the whole 4MB buffer per batch).
            raw = ctypes.string_at(buf, used.value)
            with self._mu:
                for tag, _rid, status, payload in _unpack_records(
                        raw, used.value):
                    fut = self._futs.pop(tag, None)
                    if fut is not None:
                        batch.append((fut, status, payload))
            if batch:
                try:
                    self._loop.call_soon_threadsafe(self._resolve, batch)
                except RuntimeError:
                    return  # loop closed during shutdown

    @staticmethod
    def _resolve(batch):
        for fut, status, payload in batch:
            if fut.cancelled():
                continue
            if status == 0:
                fut.set_result(payload)
            else:
                fut.set_exception(
                    ConnClosedError("worker connection closed"))

    def close(self):
        self._closed = True
        if self._poller.is_alive():
            self._poller.join(timeout=1.0)
        self._l.tpt_client_close(self._h)
        self._h = None


class NativeReceiver:
    """Worker-side server + executor pump.

    `handler(payload: bytes, reply: Callable[[bytes], None])` is invoked on
    the executor thread for every received task, in per-connection FIFO
    order; it either replies synchronously or hands off and replies later
    (async actors).
    """

    POP_BUF = 4 << 20

    def __init__(self, handler: Callable, host: str = "127.0.0.1"):
        self._l = lib()
        h = ctypes.c_void_p()
        port = ctypes.c_int()
        rc = self._l.tpt_server_new(host.encode(), 0, ctypes.byref(h),
                                    ctypes.byref(port))
        if rc != 0:
            raise OSError(f"tpt_server_new failed: {rc}")
        self._h = h
        self.port = port.value
        self._handler = handler
        self._closed = False
        self._exec = threading.Thread(
            target=self._exec_loop, daemon=True, name="tpt-exec")
        self._exec.start()

    def _exec_loop(self):
        cap = self.POP_BUF
        buf = ctypes.create_string_buffer(cap)
        used = ctypes.c_uint64()
        while not self._closed:
            n = self._l.tpt_server_pop(self._h, buf, cap,
                                       ctypes.byref(used), 200)
            if n == TPT_EBUF:
                cap = max(cap * 2, int(used.value))
                buf = ctypes.create_string_buffer(cap)
                continue
            if n <= 0:
                continue
            raw = ctypes.string_at(buf, used.value)
            for tag, req_id, _status, payload in _unpack_records(
                    raw, used.value):
                reply = self._make_reply(tag, req_id)
                try:
                    self._handler(payload, reply)
                except BaseException:
                    logger.exception("native task handler failed")

    def _make_reply(self, tag: int, req_id: int):
        def reply(data: bytes):
            self._l.tpt_server_reply(self._h, tag, req_id, data, len(data))
        return reply

    def close(self):
        self._closed = True
        if self._exec.is_alive():
            self._exec.join(timeout=1.0)
        self._l.tpt_server_close(self._h)
        self._h = None
