"""Python binding for the native task-submission transport (taskrpc.cc).

Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
(submitter: pipelined PushTask over leased workers) and
direct_actor_transport.h:50 (receiver-side ordered execution queues).  The
C++ plane owns connections, framing, pipelining, and batched completion
delivery; Python supplies payload bytes (pickled TaskSpec) on one side and
executes user functions on the other.

Submitter: `NativeSubmitter.call(addr, payload)` is awaitable on the core
worker's event loop.  A single poller thread drains completion batches from
C++ and resolves futures with ONE loop wakeup per batch.

Receiver: `NativeReceiver` runs a C++ server plus an executor thread that
pops task batches; each task is handed to a handler callable
(payload) -> bytes | awaitable-scheduler, and the reply streams back
through the C++ writer.
"""

from __future__ import annotations

import asyncio
import contextlib
import ctypes
import logging
import os
import struct
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

_REC_HDR = struct.Struct("<QQiQ")  # tag, req_id, status, payload_len
_FRAME_HDR = struct.Struct("<IQ")  # frame_len, req_id (wire framing)
_U64 = struct.Struct("<Q")

TPT_OK = 0
TPT_ECONN = -1
TPT_EBUF = -4  # head record exceeds caller buffer; `used` = needed size


class _Lib:
    """Two views of libtpttask: fast entry points go through PyDLL (GIL
    HELD — they only enqueue + memcpy, and releasing/reacquiring the GIL
    per call costs more than the call under thread contention), while the
    blocking poll/pop go through CDLL (GIL released while waiting)."""

    def __init__(self):
        from ray_tpu import _native
        path = _native.lib_path("tpttask")
        fast = ctypes.PyDLL(path)
        blocking = ctypes.CDLL(path)
        fast.tpt_client_new.argtypes = [ctypes.POINTER(ctypes.c_void_p)]
        fast.tpt_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
        fast.tpt_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_uint64, ctypes.c_char_p,
                                  ctypes.c_uint64]
        fast.tpt_send_raw.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                      ctypes.c_char_p, ctypes.c_uint64]
        fast.tpt_set_caller.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64]
        fast.tpt_register_template.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        fast.tpt_send_specs.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        ctypes.c_char_p, ctypes.c_uint64]
        fast.tpt_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        blocking.tpt_poll.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_int]
        fast.tpt_completion_fd.argtypes = [ctypes.c_void_p]
        fast.tpt_completion_fd.restype = ctypes.c_int
        blocking.tpt_client_close.argtypes = [ctypes.c_void_p]
        fast.tpt_server_new.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_void_p),
                                        ctypes.POINTER(ctypes.c_int)]
        blocking.tpt_server_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_uint64,
                                           ctypes.POINTER(ctypes.c_uint64),
                                           ctypes.c_int]
        fast.tpt_server_reply.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                          ctypes.c_uint64, ctypes.c_char_p,
                                          ctypes.c_uint64]
        fast.tpt_server_reply_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        blocking.tpt_server_close.argtypes = [ctypes.c_void_p]
        self.tpt_client_new = fast.tpt_client_new
        self.tpt_connect = fast.tpt_connect
        self.tpt_send = fast.tpt_send
        self.tpt_send_raw = fast.tpt_send_raw
        self.tpt_set_caller = fast.tpt_set_caller
        self.tpt_completion_fd = fast.tpt_completion_fd
        self.tpt_register_template = fast.tpt_register_template
        self.tpt_send_specs = fast.tpt_send_specs
        self.tpt_close_conn = fast.tpt_close_conn
        self.tpt_poll = blocking.tpt_poll
        self.tpt_client_close = blocking.tpt_client_close
        self.tpt_server_new = fast.tpt_server_new
        self.tpt_server_pop = blocking.tpt_server_pop
        self.tpt_server_reply = fast.tpt_server_reply
        self.tpt_server_reply_raw = fast.tpt_server_reply_raw
        self.tpt_server_close = blocking.tpt_server_close


def _load():
    return _Lib()


_lib = None
_lib_lock = threading.Lock()


def lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            _lib = _load()
        return _lib


def _unpack_records(buf: bytes, used: int):
    """Yield (tag, req_id, status, payload) records from a packed batch."""
    off = 0
    while off < used:
        tag, req_id, status, plen = _REC_HDR.unpack_from(buf, off)
        off += _REC_HDR.size
        payload = bytes(buf[off:off + plen])
        off += plen
        yield tag, req_id, status, payload


class ConnClosedError(ConnectionError):
    """The worker connection died with this request in flight."""


class NativeSubmitter:
    """Driver/owner-side pipelined task pusher.

    Hot-path locking: `call_cb`/`call` run only on the owning event-loop
    thread, so request registration needs no lock (dict ops are atomic
    under the GIL and the completion for a request cannot arrive before
    its `tpt_send`).  The poller thread pops completions with atomic
    `dict.pop` and hands the batch to the loop in ONE wakeup.  `_mu`
    guards only the (cold) connection map."""

    # Initial completion-batch buffer; TPT_EBUF grows it on demand.
    # Small start matters: create_string_buffer zeroes the allocation,
    # and every forked worker pays it at boot.
    POLL_BUF = 256 << 10

    def __init__(self, loop):
        import itertools
        self._loop = loop
        self._l = lib()
        h = ctypes.c_void_p()
        rc = self._l.tpt_client_new(ctypes.byref(h))
        if rc != 0:
            raise OSError(f"tpt_client_new failed: {rc}")
        self._h = h
        self._conns: dict[str, int] = {}
        self._cbs: dict[int, object] = {}   # req_id -> cb(status, payload)
        self._tpl_ids: set[int] = set()     # templates pushed to C
        self._req_iter = itertools.count(1)
        self._mu = threading.Lock()
        self._closed = False
        # In-flight sender count: zero-hop dispatch sends from arbitrary
        # submitting threads, so close() must not free the C client
        # under a live tpt_send_specs call.
        self._users = 0
        self._users_mu = threading.Lock()
        # Completion delivery: the loop watches the library's completion
        # eventfd directly and drains batches inline — no poller thread,
        # no call_soon_threadsafe handoff (one fewer context switch per
        # completion batch on a one-core host).
        self._cap = self.POLL_BUF
        self._buf = ctypes.create_string_buffer(self._cap)
        self._used = ctypes.c_uint64()
        self._cfd = self._l.tpt_completion_fd(self._h)
        loop.call_soon_threadsafe(
            loop.add_reader, self._cfd, self._drain_completions)

    # -- connection management -------------------------------------------

    def connect(self, addr: str) -> int:
        """Idempotent connect; returns the conn tag for `host:port`."""
        with self._mu:
            tag = self._conns.get(addr)
            if tag is not None:
                return tag
        host, port = addr.rsplit(":", 1)
        out = ctypes.c_uint64()
        rc = self._l.tpt_connect(self._h, host.encode(), int(port),
                                 ctypes.byref(out))
        if rc != 0:
            raise ConnectionError(f"native connect to {addr} failed ({rc})")
        with self._mu:
            self._conns[addr] = out.value
        return out.value

    def invalidate(self, addr: str):
        with self._mu:
            tag = self._conns.pop(addr, None)
        if tag is not None:
            self._l.tpt_close_conn(self._h, tag)

    # -- submission -------------------------------------------------------

    def call_cb(self, addr: str, payload: bytes, cb) -> None:
        """Push a request; `cb(status, payload_bytes)` runs on the owning
        loop when the reply (or transport failure) arrives.  Zero futures,
        zero per-request loop callbacks: completions are delivered a
        BATCH per loop wakeup and cbs run inline.

        Failure callbacks are DEFERRED via call_soon: callers dispatch
        from inside scheduler loops, and a synchronous error callback
        would re-enter them mid-iteration (the future-based API always
        deferred; this preserves that contract)."""
        from ray_tpu._private.fault_injection import get_chaos
        chaos = get_chaos()
        if chaos is not None and (chaos.native_drop()
                                  or chaos.link_fault(addr)):
            # Injected drop / scripted link blackhole: surface as a
            # transport failure so the caller's worker-death/retry path
            # handles it.
            self._loop.call_soon(cb, TPT_ECONN, b"")
            return
        try:
            tag = self.connect(addr)
        except ConnectionError:
            self._loop.call_soon(cb, TPT_ECONN, b"")
            return
        req_id = next(self._req_iter)
        self._cbs[req_id] = cb
        rc = self._l.tpt_send(self._h, tag, req_id, payload, len(payload))
        if rc != 0:
            self._cbs.pop(req_id, None)
            self.invalidate(addr)
            self._loop.call_soon(cb, TPT_ECONN, b"")

    def set_caller(self, caller_id: bytes) -> None:
        """Bake the submitting worker's id into every encoded
        PushTaskRequest (PushTaskRequest.caller_id)."""
        self._l.tpt_set_caller(self._h, caller_id, len(caller_id))

    def register_template(self, tpl_id: int, tpl: bytes) -> None:
        """Register the serialized constant-field TaskSpecP prefix for
        `tpl_id` (idempotent; cold path — once per (fn, options))."""
        if tpl_id in self._tpl_ids:
            return
        self._l.tpt_register_template(self._h, tpl_id, tpl, len(tpl))
        self._tpl_ids.add(tpl_id)

    def call_spec_batch(self, addr: str, items) -> None:
        """Push a burst of task descriptors to one worker: the library
        splices each descriptor with its registered template into
        TaskSpecP/PushTaskRequest wire bytes (taskrpc.cc codec) — no
        Python serialization of the spec at all.  `items` is a sequence
        of (desc_bytes, template, cb) where `template` is (tpl_id,
        tpl_bytes).  Callable from the loop OR a submitting thread
        (zero-hop dispatch); failure callbacks land on the loop either
        way."""
        from ray_tpu._private.fault_injection import get_chaos
        chaos = get_chaos()
        if chaos is not None:
            kept = []
            for it in items:
                if chaos.native_drop() or chaos.link_fault(addr):
                    try:
                        self._loop.call_soon_threadsafe(it[2], TPT_ECONN,
                                                        b"")
                    except RuntimeError:
                        pass
                else:
                    kept.append(it)
            items = kept
            if not items:
                return
        with self._users_mu:
            if self._closed:
                for _d, _t, cb in items:
                    try:
                        self._loop.call_soon_threadsafe(cb, TPT_ECONN, b"")
                    except RuntimeError:
                        pass
                return
            self._users += 1
        try:
            try:
                tag = self.connect(addr)
            except ConnectionError:
                for _d, _t, cb in items:   # deferred: see call_cb
                    self._loop.call_soon_threadsafe(cb, TPT_ECONN, b"")
                return
            cbs = self._cbs
            parts = []
            ids = []
            pack = _U64.pack
            for desc, tpl, cb in items:
                if tpl[0] not in self._tpl_ids:
                    self.register_template(*tpl)
                req_id = next(self._req_iter)
                cbs[req_id] = cb
                ids.append(req_id)
                parts.append(pack(req_id))
                parts.append(desc)
            blob = b"".join(parts)
            rc = self._l.tpt_send_specs(self._h, tag, blob, len(blob))
            if rc != 0:
                self.invalidate(addr)
                for req_id, (_d, _t, cb) in zip(ids, items):
                    if cbs.pop(req_id, None) is not None:
                        self._loop.call_soon_threadsafe(cb, TPT_ECONN, b"")
        finally:
            with self._users_mu:
                self._users -= 1

    def call(self, addr: str, payload: bytes):
        """Awaitable variant: returns an asyncio future on the owning
        loop (await it there)."""
        fut = self._loop.create_future()

        def cb(status, data):
            if fut.cancelled():
                return
            if status == 0:
                fut.set_result(data)
            else:
                fut.set_exception(
                    ConnClosedError("worker connection closed"))
        self.call_cb(addr, payload, cb)
        return fut

    # -- completion pump --------------------------------------------------

    def _drain_completions(self):
        """add_reader callback: drain every queued completion batch and
        run callbacks inline (we ARE on the owning loop)."""
        try:
            os.read(self._cfd, 8)   # clear the counting eventfd
        except (BlockingIOError, OSError):
            pass
        pops = self._cbs.pop
        while not self._closed and self._h is not None:
            n = self._l.tpt_poll(self._h, self._buf, self._cap,
                                 ctypes.byref(self._used), 0)
            if n == TPT_EBUF:
                # Oversized head record: grow and retry (the bigger
                # buffer sticks, so growth is amortized).
                self._cap = max(self._cap * 2, int(self._used.value))
                self._buf = ctypes.create_string_buffer(self._cap)
                continue
            if n <= 0:
                return
            # string_at copies only the used prefix (buf.raw would copy
            # the whole 4MB buffer per batch).
            raw = ctypes.string_at(self._buf, self._used.value)
            for tag, _rid, status, payload in _unpack_records(
                    raw, self._used.value):
                cb = pops(tag, None)
                if cb is not None:
                    try:
                        cb(status, payload)
                    except Exception:
                        logger.exception(
                            "native completion callback failed")

    def close(self):
        """Tear down from any thread.  The reader must be detached ON
        the loop (and any in-flight _drain_completions finished — the
        loop is single-threaded, so once _detach has run no drain can be
        executing) BEFORE the C client is freed, else the loop races a
        use-after-free."""
        with self._users_mu:       # new senders bounce off the flag
            self._closed = True
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            # Called on the owning loop: no drain can be concurrently
            # executing (single-threaded loop) — detach inline.
            try:
                self._loop.remove_reader(self._cfd)
            except Exception:
                pass
        else:
            detached = threading.Event()

            def _detach():
                try:
                    self._loop.remove_reader(self._cfd)
                except Exception:
                    pass
                detached.set()
            try:
                if self._loop.is_closed():
                    detached.set()
                else:
                    self._loop.call_soon_threadsafe(_detach)
            except RuntimeError:
                detached.set()   # loop already closed: no reader can run
            if not detached.wait(5.0):
                # The loop is wedged (storm overload): freeing the C
                # client now risks a use-after-free if the reader fires
                # later.  Leak it — close() only runs at process
                # teardown.
                logger.warning("completion reader still attached; "
                               "leaking native client")
                self._h = None
                return
        # Wait out in-flight senders (zero-hop threads inside
        # tpt_send_specs); new ones bounce off _closed.
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._users_mu:
                if self._users == 0:
                    break
            time.sleep(0.005)
        else:
            logger.warning("senders still in flight; leaking native "
                           "client")
            self._h = None
            return
        self._l.tpt_client_close(self._h)
        self._h = None


class NativeReceiver:
    """Worker-side server + executor pump.

    `handler(payload: bytes, reply: Callable[[bytes], None])` is invoked on
    the executor thread for every received task, in per-connection FIFO
    order; it either replies synchronously or hands off and replies later
    (async actors).

    Replies produced synchronously while an execution batch is being
    drained are accumulated and flushed per connection in ONE pre-framed
    library call (tpt_server_reply_raw): a per-reply enqueue costs an
    eventfd wake — a context switch on small hosts — where a batch costs
    one.  Replies from any other thread (async actors, thread-pool
    actors) go out immediately via the classic per-reply path.
    """

    POP_BUF = 256 << 10   # grows on TPT_EBUF, like POLL_BUF

    def __init__(self, handler: Callable, host: str = "127.0.0.1"):
        self._l = lib()
        h = ctypes.c_void_p()
        port = ctypes.c_int()
        rc = self._l.tpt_server_new(host.encode(), 0, ctypes.byref(h),
                                    ctypes.byref(port))
        if rc != 0:
            raise OSError(f"tpt_server_new failed: {rc}")
        self._h = h
        self.port = port.value
        self._handler = handler
        self._closed = False
        # Per-thread reply batches: a thread inside batch_scope() has its
        # replies accumulated and flushed in one call per conn at scope
        # exit; all other threads reply immediately.
        self._batches: dict[int, dict] = {}
        # Event-loop threads registered for per-tick coalescing (async
        # actors): replies accumulate across one loop tick and flush via
        # a call_soon'd drain.
        self._tick: dict[int, list] = {}   # ident -> [loop, batch dict]
        self._exec = threading.Thread(
            target=self._exec_loop, daemon=True, name="tpt-exec")
        self._exec.start()

    @contextlib.contextmanager
    def batch_scope(self):
        """Accumulate this thread's synchronous replies; flush per conn in
        one pre-framed call at exit (used around execution bursts).
        Between tasks of a burst, callers invoke flush_thread_batch()
        after any slow task so a fast task's reply is never held behind a
        slow neighbour (head-of-line)."""
        ident = threading.get_ident()
        outer = self._batches.get(ident)
        self._batches[ident] = {}
        try:
            yield
        finally:
            batch = self._batches.pop(ident, {})
            if outer is not None:
                self._batches[ident] = outer
            self._flush(batch)

    def flush_thread_batch(self) -> None:
        """Ship this thread's accumulated replies NOW (keeps the scope
        open for subsequent tasks in the burst)."""
        batch = self._batches.get(threading.get_ident())
        if batch:
            drained = dict(batch)
            batch.clear()
            self._flush(drained)

    def _flush(self, batch: dict) -> None:
        for tag, frames in batch.items():
            blob = b"".join(frames)
            self._l.tpt_server_reply_raw(self._h, tag, blob, len(blob))

    def _exec_loop(self):
        from ray_tpu._private.profiling import start_periodic_profile
        start_periodic_profile("RAY_TPU_PROFILE_EXEC", "exec")
        cap = self.POP_BUF
        buf = ctypes.create_string_buffer(cap)
        used = ctypes.c_uint64()
        while not self._closed:
            n = self._l.tpt_server_pop(self._h, buf, cap,
                                       ctypes.byref(used), 200)
            if n == TPT_EBUF:
                cap = max(cap * 2, int(used.value))
                buf = ctypes.create_string_buffer(cap)
                continue
            if n <= 0:
                continue
            raw = ctypes.string_at(buf, used.value)
            with self.batch_scope():
                for tag, req_id, _status, payload in _unpack_records(
                        raw, used.value):
                    reply = self._make_reply(tag, req_id)
                    t0 = time.monotonic()
                    try:
                        self._handler(payload, reply)
                    except BaseException:
                        logger.exception("native task handler failed")
                    if time.monotonic() - t0 > 0.002:
                        # A slow task must not hold earlier fast tasks'
                        # replies hostage for the rest of the burst.
                        self.flush_thread_batch()

    def enable_tick_batching(self, loop):
        """Coalesce replies produced on `loop`'s thread across one loop
        tick (async-actor completions land many per tick; each direct
        reply would cost an io wakeup)."""
        def _register():
            self._tick[threading.get_ident()] = [loop, {}]
        loop.call_soon_threadsafe(_register)

    def _flush_tick(self, ident):
        entry = self._tick.get(ident)
        if entry is None:
            return
        batch, entry[1] = entry[1], {}
        self._flush(batch)

    def _make_reply(self, tag: int, req_id: int):
        def reply(data: bytes):
            ident = threading.get_ident()
            batch = self._batches.get(ident)
            if batch is not None:
                batch.setdefault(tag, []).append(
                    _FRAME_HDR.pack(8 + len(data), req_id) + data)
                return
            tick = self._tick.get(ident)
            if tick is not None:
                if not tick[1]:
                    tick[0].call_soon(self._flush_tick, ident)
                tick[1].setdefault(tag, []).append(
                    _FRAME_HDR.pack(8 + len(data), req_id) + data)
                return
            self._l.tpt_server_reply(self._h, tag, req_id, data,
                                     len(data))
        return reply

    def close(self):
        self._closed = True
        if self._exec.is_alive():
            self._exec.join(timeout=1.0)
        self._l.tpt_server_close(self._h)
        self._h = None
