"""Runtime environments: per-task/actor env vars + code + packages.

Reference parity: python/ray/_private/runtime_env/ — working_dir.py /
py_modules.py (zip upload to GCS KV, content-addressed, cached per node),
pip.py (per-env package installation, cached by requirements hash), and
the plugin descriptor plumbing through the raylet worker pool
(worker_pool.h:245 runtime-env-hash worker caching).

pip installs come from a LOCAL WHEELHOUSE (`--no-index --find-links`):
this deployment is zero-egress, so packages must be pre-staged as wheels
on every node (RAY_TPU_WHEELHOUSE or the descriptor's `wheelhouse` path)
— the reference's pip.py network fetch replaced by an offline resolve
with identical isolation semantics (per-requirements-hash target dir
prepended to sys.path, shared by same-env workers on a node).  conda
remains gated: a conda solve cannot run offline.

Descriptor shape (what travels in TaskSpec.runtime_env after packaging):
    {"env_vars": {...},
     "working_dir_key": "pkg:<sha1>",       # GCS KV key
     "py_module_keys": ["pkg:<sha1>", ...],
     "pip": {"packages": [...], "wheelhouse": "/abs/path"}}
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "conda"}
_PKG_NS = "pkg"


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        base = os.path.abspath(path)
        for root, _dirs, files in os.walk(base):
            if "__pycache__" in root:
                continue
            for name in files:
                full = os.path.join(root, name)
                z.write(full, os.path.relpath(full, base))
    return buf.getvalue()


async def build_descriptor(runtime_env: Dict[str, Any], kv_call
                           ) -> Dict[str, Any]:
    """Validate + package a user runtime_env; uploads code archives to the
    GCS KV under content hashes.  kv_call: async (method, request)."""
    unknown = set(runtime_env) - _SUPPORTED
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)}; "
                         f"supported: {sorted(_SUPPORTED)}")
    if runtime_env.get("conda"):
        raise NotImplementedError(
            "runtime_env conda environments need a network solve; this "
            "deployment is zero-egress — use pip with a local wheelhouse "
            "or bake dependencies into the image")
    desc: Dict[str, Any] = {}
    pip_spec = runtime_env.get("pip")
    if pip_spec:
        if isinstance(pip_spec, (list, tuple)):
            packages, wheelhouse = list(pip_spec), None
        elif isinstance(pip_spec, dict):
            packages = list(pip_spec.get("packages", []))
            wheelhouse = pip_spec.get("wheelhouse")
        else:
            raise ValueError(
                "runtime_env pip must be a list of requirements or "
                "{'packages': [...], 'wheelhouse': path}")
        wheelhouse = wheelhouse or os.environ.get("RAY_TPU_WHEELHOUSE")
        if not wheelhouse:
            raise ValueError(
                "runtime_env pip needs a local wheelhouse (zero-egress): "
                "set RAY_TPU_WHEELHOUSE or pass "
                "pip={'packages': [...], 'wheelhouse': path}")
        if not packages:
            raise ValueError("runtime_env pip: no packages listed")
        desc["pip"] = {"packages": sorted(packages),
                       "wheelhouse": os.path.abspath(wheelhouse)}
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in env_vars.items()):
            raise ValueError("runtime_env env_vars must be str -> str")
        desc["env_vars"] = dict(env_vars)

    async def upload(path: str) -> str:
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env path is not a directory: {path}")
        blob = _zip_dir(path)
        key = f"{_PKG_NS}:{hashlib.sha1(blob).hexdigest()}"
        await kv_call("kv_put", {"ns": _PKG_NS, "key": key, "value": blob,
                                 "overwrite": False})
        return key

    if runtime_env.get("working_dir"):
        desc["working_dir_key"] = await upload(runtime_env["working_dir"])
    if runtime_env.get("py_modules"):
        desc["py_module_keys"] = [await upload(p)
                                  for p in runtime_env["py_modules"]]
    return desc


def env_hash(descriptor: Optional[Dict[str, Any]]) -> str:
    """Stable worker-pool cache key (reference: runtime-env hash,
    worker_pool.h:156)."""
    if not descriptor:
        return ""
    return hashlib.sha1(
        json.dumps(descriptor, sort_keys=True).encode()).hexdigest()[:16]


async def setup_in_worker(descriptor: Dict[str, Any], kv_call,
                          cache_root: str) -> None:
    """Worker-side activation: fetch + extract archives (content-addressed
    cache shared by workers on the node), chdir into working_dir, prepend
    py_modules to sys.path.  env_vars were applied by the daemon at spawn."""
    if not descriptor:
        return

    async def fetch_extract(key: str) -> str:
        dest = os.path.join(cache_root, key.replace(":", "_"))
        if not os.path.isdir(dest):
            reply = await kv_call("kv_get", {"ns": _PKG_NS, "key": key})
            blob = reply["value"]
            if blob is None:
                raise RuntimeError(f"runtime_env package {key} not in GCS")
            tmp = dest + f".tmp{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(blob)) as z:
                z.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:  # another worker won the race
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)
        return dest

    for key in descriptor.get("py_module_keys", []):
        path = await fetch_extract(key)
        if path not in sys.path:
            sys.path.insert(0, path)
    if descriptor.get("working_dir_key"):
        path = await fetch_extract(descriptor["working_dir_key"])
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    if descriptor.get("pip"):
        path = _ensure_pip_env(descriptor["pip"], cache_root)
        if path not in sys.path:
            sys.path.insert(0, path)


def _ensure_pip_env(pip_desc: Dict[str, Any], cache_root: str) -> str:
    """Install the requirement set from the local wheelhouse into a
    per-hash target dir (reference: pip.py's per-env virtualenv, cached
    by requirements hash).  `--no-index` keeps the resolve offline."""
    import hashlib as _hashlib
    import subprocess
    packages = pip_desc["packages"]
    wheelhouse = pip_desc["wheelhouse"]
    tag = _hashlib.sha1(json.dumps(
        [packages, wheelhouse], sort_keys=True).encode()).hexdigest()[:16]
    dest = os.path.join(cache_root, f"pip_{tag}")
    if os.path.isdir(dest):
        return dest
    if not os.path.isdir(wheelhouse):
        raise RuntimeError(
            f"runtime_env pip wheelhouse does not exist on this node: "
            f"{wheelhouse}")
    tmp = dest + f".tmp{os.getpid()}"
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--quiet",
         "--no-index", "--find-links", wheelhouse,
         "--target", tmp, *packages],
        capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"pip install from wheelhouse failed: {proc.stderr.strip()}")
    try:
        os.replace(tmp, dest)
    except OSError:  # another worker won the race
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return dest
