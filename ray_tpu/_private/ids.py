"""Binary identifiers for every entity in the runtime.

Design follows the reference's ID scheme (src/ray/common/id.h): fixed-width
binary IDs with lineage embedded (an ObjectID embeds the TaskID that produced
it; a TaskID embeds the ActorID/JobID context).  We keep the same widths so
tooling expectations (hex string lengths) carry over, but generation is
simplified: random unique bytes + embedded parent prefixes.
"""

from __future__ import annotations

import os
import random
import threading

_UNIQUE_SIZE = 16  # random portion

# ID entropy comes from a per-process PRNG seeded from the OS once (plus
# re-seeding after fork): os.urandom is a syscall (~50us inside cgroups)
# and sat directly on the task-submission hot path at one TaskID + N
# ObjectIDs per task.
_rng = random.Random(os.urandom(16))
_rng_pid = os.getpid()
_rng_lock = threading.Lock()


def _rand_bytes(n: int) -> bytes:
    global _rng, _rng_pid
    with _rng_lock:
        if os.getpid() != _rng_pid:  # forked child must not replay parent
            _rng = random.Random(os.urandom(16))
            _rng_pid = os.getpid()
        return _rng.getrandbits(n * 8).to_bytes(n, "little")


class BaseID:
    SIZE = 20
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._bytes = binary
        # IDs key every hot dict (object table, pending tasks); caching
        # the hash skips a hash(bytes) call per lookup.
        self._hash = hash(binary)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls(cls._counter.to_bytes(4, "little"))


class NodeID(BaseID):
    SIZE = 20


class WorkerID(BaseID):
    SIZE = 20


class ActorID(BaseID):
    """12 random bytes + 4-byte job id."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class TaskID(BaseID):
    """8 random bytes + 16-byte actor id (nil for normal tasks)."""

    SIZE = 24

    @classmethod
    def of(cls, actor_id: "ActorID | None" = None) -> "TaskID":
        aid = actor_id.binary() if actor_id is not None else b"\x00" * ActorID.SIZE
        return cls(_rand_bytes(cls.SIZE - ActorID.SIZE) + aid)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[-ActorID.SIZE:])


class ObjectID(BaseID):
    """TaskID (24 bytes) + 4-byte little-endian return index = 28 bytes.

    Mirrors the reference's lineage-embedded ObjectID: given an ObjectID we
    can recover the task that produces it, which is what makes lineage
    reconstruction possible without a central map.
    """

    SIZE = 28

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid colliding with
        # return indices.
        return cls(task_id.binary() + (0x8000_0000 | put_index).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE:], "little") & 0x7FFF_FFFF

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[TaskID.SIZE:], "little") & 0x8000_0000)


class PlacementGroupID(BaseID):
    SIZE = 16


__all__ = [
    "BaseID",
    "JobID",
    "NodeID",
    "WorkerID",
    "ActorID",
    "TaskID",
    "ObjectID",
    "PlacementGroupID",
]
