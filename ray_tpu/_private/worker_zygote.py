"""Fork-server ("zygote") for fast worker spawn.

Reference parity: the raylet's worker prestart pool (worker_pool.h:245
PrestartWorkers + maximum_startup_concurrency) exists because cold Python
worker boot is the latency floor for task fan-out, actor creation storms
and autoscaler response.  This build goes one step further than
prestarting: the hostd keeps ONE template process that has already paid
the interpreter + import cost (~0.3s on a small host), and every non-TPU
worker is an os.fork() of it (~1-2ms, memory shared copy-on-write).

Protocol (line-delimited JSON over the zygote's stdin/stdout):
  hostd -> zygote: {"argv": [...], "env": {k: v}, "stdout": path, "stderr": path}
  zygote -> hostd: {"pid": <child pid>}       (one reply per request)
  hostd -> zygote: {"spawn": [<req>, ...]}    (batched: K forks per wakeup)
  zygote -> hostd: {"pids": [<pid>, ...]}     (order matches the request)
The zygote emits {"ready": true} once imports are done.  EOF on stdin or
the hostd's death (orphan watch) shuts it down; forked children notice
the zygote's death via their own ppid watch (worker_main.orphan_watch).

TPU workers do NOT fork: PJRT/TPU runtime state must never cross a fork,
so hostd keeps the classic spawn path for them (hostd._spawn_worker).

Fork safety: the zygote is strictly single-threaded and starts no event
loops; heavy modules are imported, never initialized (no grpc channels,
no sockets, no jax).  Children re-create all runtime state.
"""

from __future__ import annotations

import json
import os
import select
import signal
import sys


_exited: dict = {}   # pid -> exit code, drained by the hostd's "reap" poll


def _reap(_sig, _frm):
    """Collect exited children, recording their REAL exit codes (the
    hostd cannot waitpid children of this process; it polls them back
    over the pipe so crashes keep their signal instead of reading as
    exit 0, and so a recycled pid is never mistaken for a live worker)."""
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return
        if os.WIFSIGNALED(status):
            _exited[pid] = -os.WTERMSIG(status)
        else:
            _exited[pid] = os.WEXITSTATUS(status)


def _child(req) -> None:
    """Runs in the forked child; becomes a full worker process."""
    # The zygote's SIGCHLD reaper must NOT survive the fork: it would
    # steal exit statuses from subprocesses the worker itself spawns
    # (pip installs, user tasks), making their failures read as rc=0.
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    os.setsid()
    out = os.open(req["stdout"], os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                  0o644)
    err = os.open(req["stderr"], os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                  0o644)
    os.dup2(out, 1)
    os.dup2(err, 2)
    os.close(out)
    os.close(err)
    env = req["env"]
    os.environ.clear()
    os.environ.update(env)
    sys.argv = ["ray_tpu_worker"] + list(req["argv"])
    code = 0
    try:
        from ray_tpu._private import worker_main
        worker_main.main()
    except SystemExit as e:
        code = e.code if isinstance(e.code, int) else (0 if e.code is None
                                                       else 1)
    except BaseException:  # noqa: BLE001 - never unwind into the fork loop
        import traceback
        traceback.print_exc()
        code = 1   # a crash must not read as a clean exit upstream
    os._exit(code)


def main() -> None:
    # Pre-import the worker stack; forks inherit it copy-on-write.  Keep
    # this list in sync with what worker_main.main touches on boot —
    # anything missed still works, it just pays its import in the child.
    import numpy  # noqa: F401
    from ray_tpu import api  # noqa: F401
    from ray_tpu._private import core_worker  # noqa: F401
    from ray_tpu._private import rpc  # noqa: F401
    from ray_tpu._private import runtime_env  # noqa: F401
    from ray_tpu._private import serialization  # noqa: F401
    from ray_tpu._private import task_transport  # noqa: F401
    from ray_tpu._private import worker_main  # noqa: F401
    from ray_tpu.util import metrics  # noqa: F401

    signal.signal(signal.SIGCHLD, _reap)

    hostd_pid = os.getppid()
    rd = sys.stdin.buffer
    wr = sys.stdout.buffer
    wr.write(b'{"ready": true}\n')
    wr.flush()
    while True:
        # select keeps the process single-threaded (fork-safe) while
        # still noticing hostd death between requests; hostd death also
        # closes the pipe, which readline reports as EOF.
        ready, _, _ = select.select([rd], [], [], 1.0)
        if not ready:
            if os.getppid() != hostd_pid:
                os._exit(0)
            continue
        line = rd.readline()
        if not line:
            os._exit(0)  # hostd closed the pipe
        try:
            req = json.loads(line)
        except ValueError:
            continue
        if req.get("reap"):
            out = dict(_exited)
            for k in out:   # pop only what was copied: a SIGCHLD between
                _exited.pop(k, None)   # copy and clear() must not be lost
            wr.write((json.dumps({"exited": list(out.items())})
                      + "\n").encode())
            wr.flush()
            continue
        if "spawn" in req:
            # Batched spawn: K forks per select wakeup, one reply line.
            pids = []
            for sub in req["spawn"]:
                pid = os.fork()
                if pid == 0:
                    _child(sub)  # never returns
                pids.append(pid)
            wr.write((json.dumps({"pids": pids}) + "\n").encode())
            wr.flush()
            continue
        pid = os.fork()
        if pid == 0:
            _child(req)  # never returns
        wr.write((json.dumps({"pid": pid}) + "\n").encode())
        wr.flush()


if __name__ == "__main__":
    main()
