"""Cluster process bootstrap
(reference: python/ray/_private/node.py Node.start_ray_processes +
services.py start_gcs_server/start_raylet).

start_head() spawns the GCS and a head hostd as subprocesses; add_node()
spawns additional hostds (the in-process multi-node simulation the reference
provides via python/ray/cluster_utils.py:99 Cluster).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
import uuid

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _daemon_env() -> dict:
    """Ensure spawned daemons can import ray_tpu regardless of driver cwd."""
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if _PKG_ROOT not in parts:
        env["PYTHONPATH"] = os.pathsep.join([_PKG_ROOT] + parts)
    return env


class ProcessGroup:
    """Tracks daemons this process spawned so shutdown can reap them."""

    def __init__(self):
        self.procs: list[subprocess.Popen] = []

    def wait(self):
        """Block until every tracked daemon exits (CLI --block mode)."""
        for p in self.procs:
            p.wait()

    def reap(self, timeout: float = 5.0):
        # Reverse order: hostds before the GCS, so each hostd can still kill
        # its workers and deregister while the control plane is up.
        for p in reversed(self.procs):
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout
        for p in reversed(self.procs):
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()


def _wait_ready_file(path: str, proc: subprocess.Popen, timeout: float = 30.0,
                     what: str = "daemon") -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read()
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} during startup "
                f"(logs in session dir)")
        time.sleep(0.02)
    raise TimeoutError(f"{what} did not become ready in {timeout}s")


def new_session_dir() -> str:
    d = os.path.join(tempfile.gettempdir(), "ray_tpu",
                     f"session_{int(time.time())}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(d, "logs"), exist_ok=True)
    return d


def start_gcs(session_dir: str, group: ProcessGroup, host="127.0.0.1",
              port: int = 0, watch_parent: bool = False) -> str:
    """watch_parent: a driver-embedded cluster (ray_tpu.init) dies with
    its driver even when the driver is SIGKILLed and atexit never runs —
    the GCS polls the driver pid and exits when it vanishes; hostds then
    follow via their GCS-unreachable watchdog.  CLI/launcher-started
    clusters must OUTLIVE the starting process, so they don't watch."""
    ready = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs", "gcs.err"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu._private.gcs",
           "--host", host, "--ready-file", ready, "--port", str(port)]
    if watch_parent:
        cmd += ["--watch-pid", str(os.getpid())]
    proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=_daemon_env())
    group.procs.append(proc)
    port = _wait_ready_file(ready, proc, what="GCS").strip()
    return f"{host}:{port}"


_hostd_spawn_seq = 0


def start_hostd(gcs_address: str, session_dir: str, group: ProcessGroup,
                *, num_cpus=None, num_tpus=None, resources=None,
                store_capacity=256 << 20, head=False,
                host="127.0.0.1") -> dict:
    global _hostd_spawn_seq
    _hostd_spawn_seq += 1
    ready = os.path.join(session_dir, f"hostd_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs",
                            f"hostd_{uuid.uuid4().hex[:6]}.err"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu._private.hostd",
           "--gcs", gcs_address, "--host", host,
           "--ready-file", ready, "--session-dir", session_dir,
           "--store-capacity", str(store_capacity)]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    if resources:
        cmd += ["--resources", ",".join(f"{k}={v}" for k, v in resources.items())]
    if head:
        cmd.append("--head")
    env = _daemon_env()
    # Hostd chaos identity: scripted node-loss scenarios name a hostd by
    # its spawn ordinal ("h1", "h2", ...).  The "h" prefix keeps hostd
    # salts disjoint from the worker spawn ordinals hostd itself stamps.
    env["RAY_TPU_CHAOS_PROC_SALT"] = f"h{_hostd_spawn_seq}"
    proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env)
    group.procs.append(proc)
    port, node_id, store_path = _wait_ready_file(
        ready, proc, what="hostd").strip().split("\n")
    return {"address": f"{host}:{port}", "node_id": node_id,
            "store_path": store_path, "proc": proc}
