"""Cluster process bootstrap
(reference: python/ray/_private/node.py Node.start_ray_processes +
services.py start_gcs_server/start_raylet).

start_head() spawns the GCS and a head hostd as subprocesses; add_node()
spawns additional hostds (the in-process multi-node simulation the reference
provides via python/ray/cluster_utils.py:99 Cluster).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _daemon_env() -> dict:
    """Ensure spawned daemons can import ray_tpu regardless of driver cwd."""
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if _PKG_ROOT not in parts:
        env["PYTHONPATH"] = os.pathsep.join([_PKG_ROOT] + parts)
    return env


class ProcessGroup:
    """Tracks daemons this process spawned so shutdown can reap them."""

    def __init__(self):
        self.procs: list[subprocess.Popen] = []
        self.supervisors: list = []  # GcsSupervisor instances

    def wait(self):
        """Block until every tracked daemon exits (CLI --block mode)."""
        for p in self.procs:
            p.wait()

    def reap(self, timeout: float = 5.0):
        # Supervisors first: a reaped GCS must read as a planned shutdown,
        # not a crash to respawn from.
        for s in self.supervisors:
            s.stop()
        self.supervisors.clear()
        # Reverse order: hostds before the GCS, so each hostd can still kill
        # its workers and deregister while the control plane is up.
        for p in reversed(self.procs):
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout
        for p in reversed(self.procs):
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()


def _wait_ready_file(path: str, proc: subprocess.Popen, timeout: float = 30.0,
                     what: str = "daemon") -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read()
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} during startup "
                f"(logs in session dir)")
        time.sleep(0.02)
    raise TimeoutError(f"{what} did not become ready in {timeout}s")


def new_session_dir() -> str:
    d = os.path.join(tempfile.gettempdir(), "ray_tpu",
                     f"session_{int(time.time())}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(d, "logs"), exist_ok=True)
    return d


def _spawn_gcs(session_dir: str, host: str, port: int, incarnation: int,
               persist: str | None, watch_pid: int | None):
    """Spawn one GCS process; returns (proc, ready_file_path)."""
    ready = os.path.join(session_dir, f"gcs_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs", "gcs.err"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu._private.gcs",
           "--host", host, "--ready-file", ready, "--port", str(port)]
    if watch_pid:
        cmd += ["--watch-pid", str(watch_pid)]
    env = _daemon_env()
    # GCS chaos identity: 'gcs0' is the first boot, 'gcs1' the first
    # supervised respawn, ... so a scripted chaos_kill_gcs_at arms per
    # incarnation (the default salts list names only 'gcs0', which is
    # what lets a respawn converge instead of re-dying forever).
    env["RAY_TPU_CHAOS_PROC_SALT"] = f"gcs{incarnation}"
    if persist:
        env["RAY_TPU_GCS_PERSIST"] = persist
    proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env)
    return proc, ready


class GcsSupervisor:
    """Respawns a crashed GCS at the SAME address from the same sqlite
    persistence path (reference: the external supervisor role ray
    operators play for GCS FT, with Redis as the durable store — here
    the launcher owns the child, and sqlite is the store).

    Clients never re-resolve anything: the respawn binds the original
    port, `_restore()` rebuilds the tables, `_reconcile_restored()` and
    the per-node anti-entropy re-registers converge the state.  A clean
    exit (rc 0: driver-watch or planned shutdown) is never respawned;
    `stop()` makes teardown read as planned even when the reap escalates
    to SIGTERM/SIGKILL."""

    def __init__(self, session_dir: str, group: ProcessGroup, host: str,
                 port: int, persist: str, proc: subprocess.Popen,
                 watch_pid: int | None, max_restarts: int):
        self.session_dir = session_dir
        self.group = group
        self.host = host
        self.port = port          # fixed after the first bind
        self.persist = persist
        self.proc = proc
        self.watch_pid = watch_pid
        self.max_restarts = max_restarts
        self.restarts = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gcs-supervisor")
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        while not self._stop.is_set():
            proc = self.proc
            while proc.poll() is None and not self._stop.wait(0.05):
                pass
            if self._stop.is_set() or proc.returncode == 0:
                return
            if self.restarts >= self.max_restarts:
                from ray_tpu.util import events
                events.record("gcs", "supervisor_gave_up",
                              restarts=self.restarts, rc=proc.returncode)
                return
            self.restarts += 1
            try:
                newproc, ready = _spawn_gcs(
                    self.session_dir, self.host, self.port, self.restarts,
                    self.persist, self.watch_pid)
                _wait_ready_file(ready, newproc, what="GCS (respawn)")
            except Exception:
                # Failed respawn burns one restart from the budget and
                # the loop immediately observes the dead child and tries
                # again (or gives up).
                continue
            try:
                idx = self.group.procs.index(proc)
                self.group.procs[idx] = newproc
            except ValueError:
                self.group.procs.append(newproc)
            self.proc = newproc
            from ray_tpu.util import events
            events.record("gcs", "supervisor_respawn",
                          incarnation=self.restarts, rc=proc.returncode,
                          address=f"{self.host}:{self.port}")


def start_gcs(session_dir: str, group: ProcessGroup, host="127.0.0.1",
              port: int = 0, watch_parent: bool = False,
              supervise: bool | None = None) -> str:
    """watch_parent: a driver-embedded cluster (ray_tpu.init) dies with
    its driver even when the driver is SIGKILLed and atexit never runs —
    the GCS polls the driver pid and exits when it vanishes; hostds then
    follow via their GCS-unreachable watchdog.  CLI/launcher-started
    clusters must OUTLIVE the starting process, so they don't watch.

    supervise (default: the `gcs_supervise` config flag): keep a
    supervisor thread that respawns a crashed GCS at the same address
    from its sqlite persistence — the head stops being a single point
    of failure.  Supervision implies persistence: when
    RAY_TPU_GCS_PERSIST is unset, a gcs.sqlite under the session dir is
    used."""
    if supervise is None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        supervise = bool(GLOBAL_CONFIG.gcs_supervise)
    persist = os.environ.get("RAY_TPU_GCS_PERSIST") or None
    if supervise and not persist:
        persist = os.path.join(session_dir, "gcs.sqlite")
    watch_pid = os.getpid() if watch_parent else None
    proc, ready = _spawn_gcs(session_dir, host, port, 0, persist, watch_pid)
    group.procs.append(proc)
    bound = int(_wait_ready_file(ready, proc, what="GCS").strip())
    if supervise:
        from ray_tpu._private.config import GLOBAL_CONFIG
        group.supervisors.append(GcsSupervisor(
            session_dir, group, host, bound, persist, proc, watch_pid,
            int(GLOBAL_CONFIG.gcs_supervisor_restarts)))
    return f"{host}:{bound}"


_hostd_spawn_seq = 0


def start_hostd(gcs_address: str, session_dir: str, group: ProcessGroup,
                *, num_cpus=None, num_tpus=None, resources=None,
                store_capacity=256 << 20, head=False,
                host="127.0.0.1") -> dict:
    global _hostd_spawn_seq
    _hostd_spawn_seq += 1
    ready = os.path.join(session_dir, f"hostd_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs",
                            f"hostd_{uuid.uuid4().hex[:6]}.err"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu._private.hostd",
           "--gcs", gcs_address, "--host", host,
           "--ready-file", ready, "--session-dir", session_dir,
           "--store-capacity", str(store_capacity)]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    if resources:
        cmd += ["--resources", ",".join(f"{k}={v}" for k, v in resources.items())]
    if head:
        cmd.append("--head")
    env = _daemon_env()
    # Hostd chaos identity: scripted node-loss scenarios name a hostd by
    # its spawn ordinal ("h1", "h2", ...).  The "h" prefix keeps hostd
    # salts disjoint from the worker spawn ordinals hostd itself stamps.
    env["RAY_TPU_CHAOS_PROC_SALT"] = f"h{_hostd_spawn_seq}"
    proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env)
    group.procs.append(proc)
    port, node_id, store_path = _wait_ready_file(
        ready, proc, what="hostd").strip().split("\n")
    return {"address": f"{host}:{port}", "node_id": node_id,
            "store_path": store_path, "proc": proc}
