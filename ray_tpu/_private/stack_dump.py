"""Shared live-thread-dump helper for `ray_tpu stack` (reference:
`ray stack`, scripts.py:1798 — py-spy over worker pids; here every
process self-reports via sys._current_frames, no ptrace)."""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict, List


def dump_threads() -> List[Dict]:
    names = {t.ident: t.name for t in threading.enumerate()}
    return [{
        "thread_id": ident,
        "name": names.get(ident, "?"),
        "stack": "".join(traceback.format_stack(frame)),
    } for ident, frame in sys._current_frames().items()]


def dump_state(events_tail: int = 50) -> Dict:
    """Threads + the flight-recorder tail: a hang report (TrainHungError,
    `cli stack`) carries the runtime's recent DECISIONS next to the
    frames, so "stuck in queue.get" comes with the lease/steal/evict
    events that led there."""
    from ray_tpu.util import events
    return {"threads": dump_threads(),
            "recent_events": events.tail(events_tail)}
