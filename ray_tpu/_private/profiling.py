"""Env-gated cProfile scaffolding for long-running runtime processes.

One helper behind every RAY_TPU_PROFILE_* / RAY_TPU_BOOT_PROFILE knob:
daemons exit via signals or os._exit, so profiles dump PERIODICALLY from
a background daemon thread — and a final flush runs on clean interpreter
exit (atexit) so the tail between the last periodic dump and shutdown is
not lost.  `stop_periodic_profiles()` flushes + stops every dumper
explicitly for teardown paths that bypass atexit.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import List


class _PeriodicProfile:
    def __init__(self, profile, path: str, interval_s: float, tag: str):
        self.profile = profile
        self.path = path
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(interval_s,), daemon=True,
            name=f"profile-{tag}")
        self._thread.start()

    def _run(self, interval_s: float):
        while not self._stop.wait(interval_s):
            self.flush()

    def flush(self):
        try:
            self.profile.dump_stats(self.path)
        except Exception:
            pass

    def stop(self):
        """Final flush + end the dumper thread (idempotent)."""
        if not self._stop.is_set():
            self._stop.set()
            self.flush()


_active: List[_PeriodicProfile] = []
_atexit_installed = False


def start_periodic_profile(env_var: str, tag: str, interval_s: float = 5.0):
    """If `env_var` names a directory, enable cProfile on the CALLING
    thread and dump `<dir>/<tag>-<pid>.prof` every `interval_s` from a
    daemon thread (plus a final flush at clean exit).  Returns the
    Profile (or None when disabled)."""
    prof_dir = os.environ.get(env_var)
    if not prof_dir:
        return None
    import cProfile
    pr = cProfile.Profile()
    pr.enable()
    path = os.path.join(prof_dir, f"{tag}-{os.getpid()}.prof")
    _active.append(_PeriodicProfile(pr, path, interval_s, tag))
    global _atexit_installed
    if not _atexit_installed:
        _atexit_installed = True
        atexit.register(stop_periodic_profiles)
    return pr


def stop_periodic_profiles() -> None:
    """Flush and stop every periodic dumper (clean-exit hook; also safe
    to call from daemon teardown paths that end in os._exit)."""
    while _active:
        _active.pop().stop()
