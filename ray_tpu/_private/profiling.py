"""Env-gated cProfile scaffolding for long-running runtime processes.

One helper behind every RAY_TPU_PROFILE_* / RAY_TPU_BOOT_PROFILE knob:
daemons exit via signals or os._exit, so profiles dump PERIODICALLY from
a background thread rather than relying on atexit.
"""

from __future__ import annotations

import os
import threading
import time


def start_periodic_profile(env_var: str, tag: str, interval_s: float = 5.0):
    """If `env_var` names a directory, enable cProfile on the CALLING
    thread and dump `<dir>/<tag>-<pid>.prof` every `interval_s`.
    Returns the Profile (or None when disabled)."""
    prof_dir = os.environ.get(env_var)
    if not prof_dir:
        return None
    import cProfile
    pr = cProfile.Profile()
    pr.enable()
    path = os.path.join(prof_dir, f"{tag}-{os.getpid()}.prof")

    def _dumper():
        while True:
            time.sleep(interval_s)
            try:
                pr.dump_stats(path)
            except Exception:
                pass

    threading.Thread(target=_dumper, daemon=True,
                     name=f"profile-{tag}").start()
    return pr
