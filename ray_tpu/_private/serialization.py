"""Value serialization with zero-copy buffer support.

Reference parity: python/ray/_private/serialization.py + the plasma-aware
pickle5 out-of-band buffer protocol.  Layout written into the object store:

    [u32 magic][u32 nseg][u64 len]*nseg  then each segment 64-byte aligned.

Segment 0 is the cloudpickle stream; segments 1..n are raw PickleBuffer
payloads (numpy/jax host buffers) recovered zero-copy from the mapped shm on
read — np.frombuffer views feed jax.device_put without a host copy.

Metadata tags the payload kind (value vs serialized exception) so readers can
re-raise remote errors without unpickling ambiguity.
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass, field

import cloudpickle

META_VALUE = b"V"
META_ERROR = b"E"
META_RAW = b"R"  # plain bytes payload, no pickle framing

_MAGIC = 0x5254B10B
_ALIGN = 64


@dataclass
class SerializedValue:
    segments: list  # list[bytes | memoryview]
    metadata: bytes = META_VALUE
    contained_refs: list = field(default_factory=list)

    @property
    def total_size(self) -> int:
        header = 8 + 8 * len(self.segments)
        size = _aligned(header)
        for seg in self.segments:
            size = _aligned(size + len(seg))
        return size

    def write_into(self, view: memoryview):
        off = 0
        struct.pack_into("<II", view, 0, _MAGIC, len(self.segments))
        off = 8
        for seg in self.segments:
            struct.pack_into("<Q", view, off, len(seg))
            off += 8
        off = _aligned(off)
        for seg in self.segments:
            n = len(seg)
            view[off: off + n] = seg
            off = _aligned(off + n)

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_into(memoryview(out))
        return bytes(out)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


import threading

_collector_tls = threading.local()


def current_ref_collector():
    """The active contained-ref collector for this thread, if any.
    ObjectRef.__reduce__ reports serialized refs here (thread-safe, unlike
    swapping the process-global ref hooks)."""
    stack = getattr(_collector_tls, "stack", None)
    return stack[-1] if stack else None


# Exact types that the C pickler serializes with semantics identical to
# cloudpickle (by value or by importable reference).  Anything else —
# notably classes/functions defined in __main__ or closures, which
# cloudpickle ships BY VALUE but C pickle would ship as a dangling
# by-reference — takes the cloudpickle path.
_FAST_TYPES = frozenset((
    type(None), bool, int, float, complex, str, bytes, bytearray,
))


def _fast_picklable(v, depth: int = 4) -> bool:
    t = type(v)
    if t in _FAST_TYPES:
        return True
    if depth:
        if t is list or t is tuple or t is set or t is frozenset:
            d = depth - 1
            return all(_fast_picklable(x, d) for x in v)
        if t is dict:
            d = depth - 1
            return all(_fast_picklable(k, d) and _fast_picklable(x, d)
                       for k, x in v.items())
    mod = getattr(t, "__module__", "")
    if mod.split(".", 1)[0] in ("numpy", "jaxlib"):
        # numpy/jaxlib arrays and scalars live in importable modules and
        # pickle by reference + raw buffers under both picklers —
        # except object-dtype arrays (elements are arbitrary) and
        # callable wrappers like np.vectorize, whose CONTENTS cloudpickle
        # ships by value but C pickle would ship as a dangling
        # by-reference to the driver's __main__.
        if callable(v):
            return False
        dt = getattr(v, "dtype", None)
        if dt is not None and getattr(dt, "hasobject", False):
            return False
        return True
    if mod == "ray_tpu.object_ref":
        return True
    return False


def serialize(value, *, ref_sink=None) -> SerializedValue:
    """Serialize `value`; contained ObjectRefs are reported to `ref_sink`."""
    contained: list = []
    stack = getattr(_collector_tls, "stack", None)
    if stack is None:
        stack = _collector_tls.stack = []
    stack.append(contained)
    try:
        buffers: list = []
        payload = None
        if _fast_picklable(value):
            # Hot path: the C pickler (~10-20x cloudpickle's pure-Python
            # Pickler) — only for values whose pickle streams are
            # identical in meaning under both.
            try:
                payload = pickle.dumps(
                    value, protocol=5, buffer_callback=buffers.append)
            except Exception:
                # Roll back EVERYTHING the aborted attempt produced:
                # ObjectRefs reduced before the failure already reported
                # into `contained`, and the retry will report them again.
                buffers.clear()
                contained.clear()
                payload = None
        if payload is None:
            payload = cloudpickle.dumps(
                value, protocol=5, buffer_callback=buffers.append)
    finally:
        stack.pop()
    segments = [payload] + [b.raw() for b in buffers]
    sv = SerializedValue(segments, META_VALUE, contained)
    if ref_sink is not None:
        for ref in contained:
            ref_sink(ref)
    return sv


def serialize_error(exc: BaseException) -> SerializedValue:
    try:
        payload = cloudpickle.dumps(exc, protocol=5)
    except Exception:
        from ray_tpu.exceptions import TaskError
        payload = cloudpickle.dumps(
            TaskError("<unserializable>", repr(exc)), protocol=5)
    return SerializedValue([payload], META_ERROR)


def deserialize(data, metadata: bytes):
    """`data`: bytes or memoryview over the framed segments."""
    if metadata == META_RAW:
        return bytes(data)
    view = memoryview(data)
    magic, nseg = struct.unpack_from("<II", view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt object payload")
    lens = struct.unpack_from(f"<{nseg}Q", view, 8)
    off = _aligned(8 + 8 * nseg)
    segments = []
    for n in lens:
        segments.append(view[off: off + n])
        off = _aligned(off + n)
    payload = segments[0]
    buffers = segments[1:]
    value = pickle.loads(bytes(payload), buffers=buffers)
    if metadata == META_ERROR:
        raise value
    return value
