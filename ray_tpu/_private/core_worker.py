"""CoreWorker — the runtime library linked into every driver and worker.

Reference parity: src/ray/core_worker/core_worker.h:284 —
Put/Get/Wait/SubmitTask/CreateActor/SubmitActorTask, plus the subsystems it
owns: in-process memory store for small objects (memory_store.h:43),
ownership-based reference counting (reference_count.h:61), the pending-task
table with retries + lineage reconstruction (task_manager.h:90), the direct
task submitter with worker leasing (transport/direct_task_transport.h:75),
and the per-actor ordered submitter (direct_actor_task_submitter.h:67).

Threading: the public API is synchronous; all networking runs on a dedicated
asyncio thread (rpc.EventLoopThread) — the same split as the reference's
Python-on-C++-asio design.  Worker-side task execution runs on the process
main thread, fed by a queue from the RPC handlers.
"""

from __future__ import annotations

import asyncio
import contextvars
import itertools
import logging
import os
import pickle as _pickle
import queue
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field

from ray_tpu import object_ref as object_ref_mod
from ray_tpu.exceptions import (
    ActorDiedError,
    ObjectLostError,
    ObjectStoreFullError,
    RayTpuTimeoutError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.object_ref import ObjectRef
from ray_tpu._private import serialization as ser
from ray_tpu._private import spec_codec
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import ObjectStore
from ray_tpu.util import spans, tracing
from ray_tpu._private.protocol import (
    INLINE_LIMIT,
    RefArg,
    Resources,
    TaskSpec,
    ValueArg,
)
from ray_tpu._private.rpc import (ClientPool, EventLoopThread, GcsClient,
                                  RpcClient, RpcServer)


def _pg_id_of(pg):
    """Accept a PlacementGroup handle, a PlacementGroupID, or None."""
    if pg is None:
        return None
    return getattr(pg, "id", pg)


@dataclass
class _BundleNode:
    """Lease target resolved from a placement-group bundle record."""
    address: str
    node_id: object

logger = logging.getLogger("ray_tpu.worker")


@dataclass
class _ObjectState:
    """Owner-side record for one owned object (directory + refcount)."""

    inline: tuple | None = None          # (data, metadata)
    locations: set = field(default_factory=set)  # node_id hex strings
    error: BaseException | None = None
    pending: bool = True
    local_refs: int = 0
    borrows: int = 0
    pins: int = 0                        # in-flight task args etc.
    event: asyncio.Event | None = None   # set when no longer pending
    waiters: list | None = None          # _BatchWaiters (bulk get)
    producing_task: TaskID | None = None


class _BatchWaiter:
    """One shared completion waiter for a bulk get(): counts outstanding
    objects and wakes the BLOCKED CALLER THREAD directly — no coroutine,
    no timer, no loop wake to start or finish a wait.  An errored object
    wakes the waiter early.  `done` may fire from the event loop (task
    completions) or a user thread (put publications); threading.Event is
    safe from both."""

    __slots__ = ("remaining", "error", "event", "lock")

    def __init__(self):
        self.remaining = 0
        self.error: BaseException | None = None
        self.event = threading.Event()
        self.lock = threading.Lock()

    def done(self, st: "_ObjectState"):
        with self.lock:
            self.remaining -= 1
            if st.error is not None and self.error is None:
                self.error = st.error
            fire = self.remaining <= 0 or st.error is not None
        if fire:
            self.event.set()


@dataclass
class _PendingTask:
    spec: TaskSpec
    retries_left: int
    future: object                       # concurrent.futures.Future | None
    lineage: bool = False                # keep spec for reconstruction
    cancelled: bool = False              # ray.cancel requested
    worker_address: str | None = None    # where the task was pushed
    payload: bytes | None = None         # packed native task descriptor
    template: tuple | None = None        # (tpl_id, TaskSpecP prefix bytes)
    sched_key: tuple | None = None       # cached _sched_key(spec, ())
    payload_epoch_base: int = 0          # sub.epoch_base baked into payload
    q_span: object = None                # open sched_queue span (traced only)


class _ActorSubmitter:
    """Client-side per-actor ordered pipeline
    (reference: direct_actor_task_submitter.h:67).

    seq is assigned in program order at submit time.  On actor restart the
    fresh worker expects wire sequence numbers from 0, so sends are rebased:
    wire_seq = seq - epoch_base, where epoch_base is the count of completed
    calls when the restart was detected (execution is in-order per actor, so
    completed calls form a prefix)."""

    def __init__(self, actor_id: ActorID):
        self.actor_id = actor_id
        self.seq = 0
        self.epoch_base = 0
        self.completed = 0
        self.address: str | None = None
        self.version = -1
        self.dead: str | None = None
        # (method, num_returns, max_retries) -> (tpl_id, TaskSpecP prefix)
        self.tpl_cache: dict = {}
        # threading.Lock: sequence numbers are assigned in the SUBMITTING
        # thread (program order), while failure rebasing happens on the
        # event loop.
        self.lock = threading.Lock()


class CoreWorker:
    def __init__(self, *, mode: str, gcs_address: str, store_path: str | None,
                 node_id: NodeID | None, hostd_address: str | None,
                 job_id: JobID | None = None, host: str = "127.0.0.1"):
        self.mode = mode                      # "driver" | "worker"
        self.worker_id = WorkerID.from_random()
        self.gcs_address = gcs_address
        self.node_id = node_id
        self.hostd_address = hostd_address
        self.host = host
        self.io = EventLoopThread()
        # GcsClient, not a bare RpcClient: control-plane calls ride
        # through supervised-GCS restarts (buffer-and-retry up to
        # gcs_outage_deadline_s) instead of failing the driver on a
        # head blip.  The data plane (tasks/objects) is peer-to-peer
        # and never routes through this channel.
        self.gcs = GcsClient(gcs_address)
        self.pool = ClientPool()
        self.store = ObjectStore.attach(store_path) if store_path else None
        self.store_path = store_path
        self.fn_manager = FunctionManager(self._kv_call)
        self.job_id = job_id
        self.objects: dict[ObjectID, _ObjectState] = {}
        self.tasks: dict[TaskID, _PendingTask] = {}
        self._pg_rr: dict = {}  # placement group -> round-robin counter
        # Lineage reconstructions in flight, by producing task: concurrent
        # getters of a lost object piggyback on one resubmission instead
        # of burning one retry each (reference:
        # object_recovery_manager.h objects_pending_recovery_).
        self._reconstructing: dict = {}   # TaskID -> asyncio.Event
        # Lease pipelining (reference: direct_task_transport.h:53-55,151 —
        # queued tasks with the same SchedulingKey reuse a held worker
        # lease instead of paying pick_node+lease+return per task).
        self._lease_cache: dict = {}      # sched_key -> _KeyScheduler
        self._free_buffer: dict = {}      # node_id -> [oid binary]
        self._free_flusher = None
        # Execution-side cancellation state (reference: CancelTask:433).
        self._cancelled_exec: set = set()
        self._running_tasks: dict = {}    # TaskID -> executing thread id
        self._cancel_lock = threading.Lock()
        self._renv_cache: dict = {}       # user runtime_env json -> descriptor
        self._opts_cache: dict = {}       # id(opts) -> (opts, invariants)
        self._tpl_ids = itertools.count(1)  # native spec-template ids
        self._tpl_content: dict = {}      # template bytes -> (id, bytes)
        self._pending_actor_reg: set = set()  # async registrations in flight
        # Loop-tick dispatch coalescing: pumps triggered by a completion
        # batch share one native flush per worker per tick.
        self._tick_batches: dict = {}
        self._tick_flush_scheduled = False
        # Task timeline events, flushed to the GCS in batches (reference:
        # core_worker/task_event_buffer.h:188).
        self._task_events: list = []
        self._task_event_flusher = None
        self.actor_submitters: dict[ActorID, _ActorSubmitter] = {}
        self.borrowed: dict[ObjectID, str] = {}  # borrowed ref -> owner addr
        self._put_index = 0
        self._obj_lock = threading.RLock()
        # Per-task execution context.  ContextVars isolate it both across
        # pool threads AND across interleaved coroutines on an async actor's
        # event loop (each asyncio.Task runs in its own context copy) —
        # thread-locals would be clobbered by concurrent async tasks.
        self._ctx_task_id: contextvars.ContextVar = \
            contextvars.ContextVar("raytpu_task_id", default=None)
        self._ctx_task_spec: contextvars.ContextVar = \
            contextvars.ContextVar("raytpu_task_spec", default=None)
        self._default_task_id = TaskID.of()   # driver context task
        self.current_actor_pg = None          # PG the actor was created in
        # Actor execution concurrency (set up at actor creation).
        self._exec_pool = None                # ThreadPoolExecutor | None
        self._async_loop = None               # asyncio loop thread | None
        self._async_sem: asyncio.Semaphore | None = None
        self.address = ""
        self._shutdown = False
        # Execution side (worker mode)
        self.exec_queue: queue.Queue = queue.Queue()
        self.actor_instance = None
        self.actor_id: ActorID | None = None
        self._actor_seq_state: dict[bytes, dict] = {}  # caller -> ordering
        self.server = RpcServer(host)
        self._register_services()
        port = self.io.run(self.server.start(0))
        self.address = f"{host}:{port}"
        # Native task transport (reference: the C++ direct task transports,
        # direct_task_transport.h:75 / direct_actor_transport.h:50).  The
        # receiver serves PushTask over the framed-TCP plane; the submitter
        # is created lazily on first use.  Target native addresses are
        # discovered once per peer via the NativePort RPC.
        self._native_sub = None
        self._native_rx = None
        self._native_addrs: dict[str, str | None] = {}
        self._native_seq_lock = threading.Lock()
        # Submit-side wakeup coalescing: one loop self-pipe write per
        # burst of submissions, not one per task.
        self._fast_q: deque = deque()
        self._fast_scheduled = False
        from ray_tpu._private.config import GLOBAL_CONFIG as _gc
        self._native_on = _gc.native_task_transport
        # Optional dispatch-coalescing window (sched_batch_wait_ms): a
        # burst's per-worker batches park in _tick_batches for up to this
        # long so trailing submissions ride the same library call.
        self._batch_wait_s = max(0.0, _gc.sched_batch_wait_ms) / 1000.0
        if mode == "worker" and _gc.native_task_transport:
            try:
                from ray_tpu._private.task_transport import NativeReceiver
                self._native_rx = NativeReceiver(
                    self._native_push_handler, host=host)
            except Exception:
                logger.exception("native task receiver unavailable; "
                                 "falling back to RPC transport")
        object_ref_mod._install_hooks(_RefHooks(self))

    # ---- per-task execution context ----------------------------------

    @property
    def current_task_id(self) -> TaskID:
        tid = self._ctx_task_id.get()
        return self._default_task_id if tid is None else tid

    @current_task_id.setter
    def current_task_id(self, value):
        self._ctx_task_id.set(value)

    @property
    def current_task_spec(self):
        return self._ctx_task_spec.get()

    @current_task_spec.setter
    def current_task_spec(self, value):
        self._ctx_task_spec.set(value)

    def _next_put_index(self) -> int:
        with self._obj_lock:
            self._put_index += 1
            return self._put_index

    # ------------------------------------------------------------------
    # RPC services (owner + execution)
    # ------------------------------------------------------------------

    def _register_services(self):
        s = self.server
        s.register("CoreWorker", "PushTask", self._rpc_push_task)
        s.register("CoreWorker", "CancelTask", self._rpc_cancel_task)
        s.register("CoreWorker", "CreateActor", self._rpc_create_actor)
        s.register("CoreWorker", "KillActor", self._rpc_kill_actor)
        s.register("CoreWorker", "GetObjectStatus", self._rpc_get_object_status)
        s.register("CoreWorker", "AddBorrow", self._rpc_add_borrow)
        s.register("CoreWorker", "RemoveBorrow", self._rpc_remove_borrow)
        s.register("CoreWorker", "AddLocation", self._rpc_add_location)
        s.register("CoreWorker", "StackTrace", self._rpc_stack_trace)
        s.register("CoreWorker", "Metrics", self._rpc_metrics)
        s.register("CoreWorker", "CollectEvents", self._rpc_collect_events)
        s.register("CoreWorker", "Ping", self._rpc_ping)
        s.register("CoreWorker", "NativePort", self._rpc_native_port)
        s.register("CoreWorker", "NodeDead", self._rpc_node_dead)
        s.register("CoreWorker", "PreemptionNotice",
                   self._rpc_preemption_notice)

    async def _rpc_preemption_notice(self, req):
        """Hostd fans its preemption notice down to each worker: this
        host dies in `grace_s` seconds.  If a train session lives here,
        arm it — its next report() races a proactive checkpoint save
        against the window, then aborts at the step boundary with
        TrainPreemptedError.  The train module is looked up, never
        imported: non-train workers must not pay the import."""
        import sys
        grace = float(req.get("grace_s", 0.0))
        from ray_tpu.util import metrics as mt
        mt.Counter("train_preemption_notices",
                   "preemption notices delivered to this worker").inc()
        sess_mod = sys.modules.get("ray_tpu.train.session")
        sess = getattr(sess_mod, "_session", None) if sess_mod else None
        if sess is not None:
            sess.notify_preemption(grace)
            return {"ok": True, "armed": True}
        return {"ok": True, "armed": False}

    async def _rpc_native_port(self, req):
        """Native-transport discovery: callers connect to this port for the
        framed-TCP PushTask plane (0 = native transport disabled here)."""
        return {"port": self._native_rx.port if self._native_rx else 0}

    async def _rpc_ping(self, req):
        return {"ok": True, "worker_id": self.worker_id}

    async def _rpc_node_dead(self, req):
        """Hostd pushes GCS-detected node death down to its workers
        (reference: raylet NodeRemoved pub/sub -> core-worker object
        directory invalidation).  Drop the dead node from every owned
        object's location set (gets fail over to live copies or lineage),
        forget its pooled channel and native route, and purge its leases
        from every key scheduler so queued work re-leases elsewhere."""
        dead_hex = req["node_id"]
        dead_addr = req.get("address") or ""
        with self._obj_lock:
            for st in self.objects.values():
                st.locations.discard(dead_hex)
        self._node_cache = None   # next _node_table() refetches live view
        if dead_addr:
            self.pool.invalidate(dead_addr)
        purged = 0
        for ks in list(self._lease_cache.values()):
            purged += ks.purge_node(dead_hex)
        if purged:
            logger.info("node %s dead: purged %d lease(s)",
                        dead_hex[:8], purged)
        return {"ok": True, "purged": purged}

    async def _kv_call(self, method: str, request):
        return await self.gcs.call("Kv", method, request)

    # ---- owner services ----

    async def _rpc_get_object_status(self, req):
        """Resolve an object for a borrower: inline value, locations, or
        error.  Long-polls while the producing task is still running
        (reference: core_worker.proto GetObjectStatus:411)."""
        oid = ObjectID(req["id"])
        wait_s = req.get("wait_s", 30.0)
        st = self.objects.get(oid)
        if st is None:
            return {"status": "unknown"}
        if st.pending:
            if st.event is None:
                st.event = asyncio.Event()
            try:
                await asyncio.wait_for(st.event.wait(), wait_s)
            except asyncio.TimeoutError:
                return {"status": "pending"}
            st = self.objects.get(oid)
            if st is None:
                return {"status": "unknown"}
        if st.error is not None:
            return {"status": "error", "error": st.error}
        if st.inline is not None:
            return {"status": "inline", "data": st.inline[0],
                    "metadata": st.inline[1]}
        return {"status": "locations", "locations": sorted(st.locations)}

    async def _rpc_add_borrow(self, req):
        st = self.objects.get(ObjectID(req["id"]))
        if st is not None:
            st.borrows += 1
        return {"ok": True}

    async def _rpc_remove_borrow(self, req):
        oid = ObjectID(req["id"])
        st = self.objects.get(oid)
        if st is not None:
            st.borrows = max(0, st.borrows - 1)
            self._maybe_free(oid)
        return {"ok": True}

    async def _rpc_add_location(self, req):
        st = self.objects.get(ObjectID(req["id"]))
        if st is not None:
            st.locations.add(req["node"])
        return {"ok": True}

    async def _rpc_stack_trace(self, req):
        """Live per-thread Python stacks + the flight-recorder tail
        (reference: `ray stack` scripts.py:1798)."""
        from ray_tpu._private.stack_dump import dump_state
        return {"pid": os.getpid(), **dump_state()}

    async def _rpc_metrics(self, req):
        """This worker's util.metrics registry, pulled by hostd into the
        node-level scrape — application metrics (serve replica engines,
        user Counters/Gauges) live here, not in the daemon."""
        from ray_tpu.util import metrics as mt
        return {"pid": os.getpid(), "metrics": mt.collect()}

    async def _rpc_collect_events(self, req):
        """This worker's flight-recorder ring (live scrape side of the
        black box).  `now` rides along so the aggregator can normalize
        clock skew across nodes."""
        from ray_tpu.util import events
        return {"pid": os.getpid(), "now": time.time(),
                "events": events.snapshot(since=req.get("since", 0.0))}

    # ---- execution services ----

    async def _rpc_cancel_task(self, req):
        """Cancel a queued or running task on this worker (reference:
        core_worker.proto CancelTask:433).  Queued -> dropped; running with
        force -> process exit; running without force -> async exception
        injected into the executing thread.  The injection happens under
        _cancel_lock, which _execute_task also holds while registering/
        deregistering, so the exception cannot target a thread that has
        already moved on to a different task."""
        from ray_tpu.exceptions import TaskCancelledError
        task_id = TaskID(req["task_id"])
        self._cancelled_exec.add(task_id)
        with self._cancel_lock:
            tid = self._running_tasks.get(task_id)
            if tid is not None:
                if req.get("force"):
                    logger.info("force-cancel: exiting worker (task %s)",
                                task_id)
                    os._exit(1)
                import ctypes
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid), ctypes.py_object(TaskCancelledError))
        return {"ok": True, "running": tid is not None}

    # ---- native-transport execution side ----

    def _native_push_handler(self, payload: bytes, reply):
        """Entry point for tasks arriving over the native plane (runs on
        the tpt-exec thread, in per-connection FIFO order).  The wire
        format is PushTaskRequest proto (raytpu.proto) — parsed by upb,
        no pickle on the control path.  Normal tasks execute inline — no
        event-loop hop; actor tasks route through the per-caller sequence
        window and the actor's concurrency mode."""
        spec = None
        try:
            spec, caller, wire_seq = spec_codec.push_request_from_wire(
                payload)
            if spec.actor_creation:
                # Creation runs on the MAIN exec thread like the RPC path
                # (actor __init__ and methods must share a thread —
                # user code may keep thread-local state).
                self.actor_id = spec.actor_id
                self.exec_queue.put(
                    (spec, self._native_done_sink(reply), None))
            elif spec.actor_id is not None:
                self._enqueue_actor_native(spec, caller, wire_seq, reply)
            else:
                self._run_one_native(spec, reply)
        except BaseException as e:  # noqa: BLE001
            try:
                reply(spec_codec.reply_to_wire(
                    self._error_reply(spec, e) if spec is not None
                    else {"returns": [], "error": TaskError(
                        "native-push", traceback.format_exc(), None)}))
            except Exception:
                logger.exception("native reply failed")

    def _run_one_native(self, spec: TaskSpec, reply):
        try:
            r = self._execute_task(spec)
        except BaseException as e:  # noqa: BLE001
            r = self._error_reply(spec, e)
        try:
            data = spec_codec.reply_to_wire(r)
        except Exception as e:
            data = spec_codec.reply_to_wire(self._error_reply(spec, e))
        reply(data)

    def _enqueue_actor_native(self, spec, caller, wire_seq, reply):
        """Per-caller in-order release, same window logic as the RPC path
        (_enqueue_actor_task) but completing via the native reply stream.
        The lock makes the window safe from the tpt-exec thread.

        Tasks are released onto the SAME exec_queue as the RPC path (with
        a callable done-sink in place of an asyncio future, loop=None):
        a sync actor with mixed-transport callers must still run its
        methods strictly serialized on the one exec thread, and the held
        window must hold one entry shape."""
        entry = (spec, self._native_done_sink(reply), None)
        with self._native_seq_lock:
            state = self._actor_seq_state.setdefault(
                caller, {"next": 0, "held": {}})
            if wire_seq < state["next"]:
                self.exec_queue.put(entry)
                return
            state["held"][wire_seq] = entry
            while state["next"] in state["held"]:
                self.exec_queue.put(state["held"].pop(state["next"]))
                state["next"] += 1

    @staticmethod
    def _native_done_sink(reply):
        def sink(r):
            try:
                reply(spec_codec.reply_to_wire(r))
            except Exception:
                logger.exception("native reply failed")
        return sink

    # ---- native-transport submission side ----

    def _ensure_native_sub(self):
        if not self._native_on:
            return None
        if self._native_sub is None:
            try:
                from ray_tpu._private.task_transport import NativeSubmitter
                self._native_sub = NativeSubmitter(self.io.loop)
                self._native_sub.set_caller(self.worker_id.binary())
            except Exception:
                logger.exception("native submitter unavailable")
                self._native_sub = False
        return self._native_sub or None

    async def _native_call_worker(self, addr: str, spec,
                                  wire_seq: int = 0) -> dict | None:
        """Push a task to `addr` (a worker's RPC address) over the native
        plane as a full PushTaskRequest proto (cold path: retries, exotic
        scheduling — the hot path uses the template codec).  Returns None
        when either side has no native transport — the caller then falls
        back to the RPC path.  Transport failures raise, like an RPC
        failure would."""
        sub = self._ensure_native_sub()
        if sub is None:
            return None
        naddr = self._native_addrs.get(addr, "?")
        if naddr == "?":
            try:
                r = await self.pool.get(addr).call(
                    "CoreWorker", "NativePort", {}, timeout=10)
                port = r.get("port") or 0
            except Exception:
                port = 0
            naddr = (f"{addr.rsplit(':', 1)[0]}:{port}" if port else None)
            self._native_addrs[addr] = naddr
        if naddr is None:
            return None
        payload = spec_codec.push_request_to_wire(
            spec, self.worker_id.binary(), wire_seq)
        try:
            data = await sub.call(naddr, payload)
        except ConnectionError:
            # Dead conn: drop the mapping so a replacement worker at the
            # same RPC address re-discovers, then surface as a failure.
            self._native_addrs.pop(addr, None)
            sub.invalidate(naddr)
            raise
        return spec_codec.reply_from_wire(data)

    async def _rpc_push_task(self, req):
        """Queue a task for the execution thread and await its result
        (reference: core_worker.proto PushTask:406)."""
        spec: TaskSpec = req["spec"]
        loop = asyncio.get_running_loop()
        done = loop.create_future()
        if spec.actor_id is not None and not spec.actor_creation:
            self._enqueue_actor_task(req, done, loop)
        else:
            self.exec_queue.put((spec, done, loop))
        return await done

    def _enqueue_actor_task(self, req, done, loop):
        """Order actor tasks per caller by sequence number
        (reference: transport/actor_scheduling_queue.h:40).

        A restarted actor starts with no ordering state while callers keep
        counting, so the first seq seen from an unknown caller initializes
        the expectation; anything below `next` is a stale retry and runs
        immediately rather than being held forever."""
        spec: TaskSpec = req["spec"]
        caller = req.get("caller", b"")
        wire_seq = req.get("seq", spec.seq_no)
        with self._native_seq_lock:  # shared with the native receiver path
            state = self._actor_seq_state.setdefault(
                caller, {"next": 0, "held": {}})
            if wire_seq < state["next"]:
                # Stale retry rebased below the horizon: run immediately.
                self.exec_queue.put((spec, done, loop))
                return
            state["held"][wire_seq] = (spec, done, loop)
            while state["next"] in state["held"]:
                item = state["held"].pop(state["next"])
                state["next"] += 1
                self.exec_queue.put(item)

    async def _rpc_create_actor(self, req):
        spec: TaskSpec = req["spec"]
        loop = asyncio.get_running_loop()
        done = loop.create_future()
        self.actor_id = req["actor_id"]
        self.exec_queue.put((spec, done, loop))
        return await done

    async def _rpc_kill_actor(self, req):
        self.exec_queue.put(None)  # sentinel: exit main loop
        asyncio.get_running_loop().call_later(0.5, os._exit, 0)
        return {"ok": True}

    # ------------------------------------------------------------------
    # Public API: put / get / wait
    # ------------------------------------------------------------------

    def put(self, value) -> ObjectRef:
        oid = ObjectID.for_put(self.current_task_id, self._next_put_index())
        sv = ser.serialize(value, ref_sink=self._pin_serialized_ref)
        try:
            self._store_owned_value(oid, sv)
        except ObjectStoreFullError:
            # Ask the node daemon to spill to disk, then retry (reference:
            # raylet SpillObjects on OOM, local_object_manager.h:41).
            for attempt in range(3):
                freed = self.io.run(self._request_spill(sv.total_size))
                try:
                    self._store_owned_value(oid, sv)
                    break
                except ObjectStoreFullError:
                    if not freed:
                        time.sleep(0.2)
            else:
                self._store_owned_value(oid, sv)
        return ObjectRef(oid, self.address)

    async def _request_spill(self, nbytes: int) -> int:
        if not self.hostd_address:
            return 0
        try:
            reply = await self.pool.get(self.hostd_address).call(
                "NodeManager", "SpillObjects",
                {"bytes_needed": int(nbytes * 1.5)}, timeout=30)
            return reply.get("freed", 0)
        except Exception:
            return 0

    def _store_owned_value(self, oid: ObjectID, sv: ser.SerializedValue):
        with self._obj_lock:
            st = self.objects.setdefault(oid, _ObjectState())
        if sv.total_size < INLINE_LIMIT or self.store is None:
            st.inline = (sv.to_bytes(), sv.metadata)
        else:
            view = self.store.create_object(oid, sv.total_size, sv.metadata)
            sv.write_into(view)
            self.store.seal(oid)
            st.locations.add(self.node_id.hex())
        # Publication order: value/locations first, THEN pending=False —
        # the caller-thread get() fast path reads states without the
        # loop, so `pending` is the publish flag.  The flip is under
        # _obj_lock: _wait_owned registration checks pending under the
        # same lock (see _signal_ready).
        with self._obj_lock:
            st.pending = False
        self._signal_ready(oid, st)

    def _signal_ready(self, oid: ObjectID, st: _ObjectState):
        if st.event is not None:
            if threading.get_ident() == self.io.ident:
                # Already on the loop: set directly — the threadsafe
                # variant writes the loop's self-pipe (~30us) per call.
                st.event.set()
            else:
                self.io.loop.call_soon_threadsafe(st.event.set)
        ws = None
        if st.waiters:
            # Pop under the same lock that guards registration: a get()
            # on another thread is either already in the list (we
            # deliver) or will see pending=False under the lock and
            # self-deliver — exactly once either way.
            with self._obj_lock:
                ws = st.waiters
                st.waiters = None
        if ws:
            for w in ws:
                w.done(st)

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        # About to block: if THIS thread holds batched native replies
        # (worker exec threads inside a burst), ship them first — a
        # caller elsewhere may be waiting on one of those replies to
        # produce the very object this get polls for (batching must
        # never introduce a cross-worker dependency deadlock).
        rx = getattr(self, "_native_rx", None)
        if rx is not None:
            rx.flush_thread_batch()
        # Caller-thread bulk path for OWNED refs: wait with ONE loop-side
        # waiter per batch (not a coroutine + timer per ref — measured
        # ~15us/ref of loop machinery), then resolve inline values right
        # here, off the event loop.  Anything non-trivial (borrowed refs,
        # store/remote copies, lost objects) falls back to the general
        # coroutine path below.  One deadline covers both phases.
        deadline = None if timeout is None else time.monotonic() + timeout
        objects = self.objects
        my_addr = self.address
        pending_refs = []
        for r in refs:
            if r.owner_address in ("", my_addr):
                st = objects.get(r.id)
                if st is not None and st.pending:
                    pending_refs.append(r)
        if pending_refs:
            self._wait_owned(pending_refs, deadline)
        values = []
        slow: list = []          # (index, ref) pairs for the general path
        for r in refs:
            st = objects.get(r.id) \
                if r.owner_address in ("", my_addr) else None
            if st is not None and not st.pending and st.error is None \
                    and st.inline is not None:
                values.append(ser.deserialize(*st.inline))
            else:
                values.append(None)
                slow.append((len(values) - 1, r))
        if slow:
            left = None if deadline is None else \
                max(0.0, deadline - time.monotonic())
            resolved = self.io.run(self._get_async(
                [r for _i, r in slow], left))
            for (i, _r), v in zip(slow, resolved):
                values[i] = v
        return values[0] if single else values

    def _wait_owned(self, refs, deadline):
        """Block the CALLING thread until every owned ref in `refs` has
        completed (value, location, or error — resolution happens back
        in get()).  One shared waiter serves the whole batch;
        registration races with completions (loop thread, put threads)
        are settled by the remove-to-deliver dance below.  An errored
        object wakes the waiter early so a failed task surfaces before
        stragglers finish."""
        waiter = _BatchWaiter()
        registered = []
        for r in refs:
            st = self.objects.get(r.id)
            if st is None or not st.pending:
                continue
            with waiter.lock:
                waiter.remaining += 1
            # Registration is atomic with the pending check under
            # _obj_lock: publication flips `pending` and pops the list
            # under the same lock, so the waiter is either delivered by
            # the publisher or self-delivered here — never both, never
            # neither.
            with self._obj_lock:
                if st.pending:
                    if st.waiters is None:
                        st.waiters = []
                    st.waiters.append(waiter)
                    registered.append(st)
                    continue
            waiter.done(st)   # completed before we got in
        try:
            while waiter.remaining > 0 and waiter.error is None:
                waiter.event.clear()
                if waiter.remaining <= 0 or waiter.error is not None:
                    break        # fired between the checks and the clear
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise RayTpuTimeoutError("get() timed out")
                if not waiter.event.wait(left):
                    raise RayTpuTimeoutError("get() timed out")
        finally:
            if waiter.remaining > 0:
                # Timed out (or errored early) with objects still
                # pending: unregister so a polling caller doesn't leak a
                # waiter per attempt into long-lived object states.
                with self._obj_lock:
                    for st in registered:
                        if st.waiters:
                            try:
                                st.waiters.remove(waiter)
                            except ValueError:
                                pass
        # An early error stops the wait; the caller-thread resolution
        # (or the per-ref fallback path) raises it in ref order.

    async def _get_async(self, refs, timeout):
        return await asyncio.gather(*[self._get_one(r, timeout) for r in refs])

    async def _get_one(self, ref: ObjectRef, timeout: float | None):
        deadline = None if timeout is None else \
            asyncio.get_running_loop().time() + timeout
        for attempt in range(5):
            data, metadata = await self._resolve_bytes(ref, deadline)
            if data is not None:
                return ser.deserialize(data, metadata)
            # Object lost: try lineage reconstruction then loop.
            if not await self._try_reconstruct(ref):
                raise ObjectLostError(ref.id, "no live copy and no lineage")
        raise ObjectLostError(ref.id, "reconstruction did not converge")

    async def _resolve_bytes(self, ref: ObjectRef, deadline):
        """Return (data, metadata) or (None, None) if the object was lost."""
        oid = ref.id
        owned = ref.owner_address in ("", self.address)
        while True:
            st = self.objects.get(oid) if owned else None
            if owned and st is None:
                raise ObjectLostError(oid, "owner has no record of object")
            if owned and not st.pending:
                if st.error is not None:
                    raise st.error
                if st.inline is not None:
                    return st.inline
                got = await self._fetch_from_locations(oid, sorted(st.locations))
                if got is not None:
                    return got
                st.locations.clear()
                return None, None
            if not owned:
                # Local store fast path before asking the owner.
                if self.store is not None:
                    buf = self.store.get(oid)
                    if buf is not None:
                        try:
                            return bytes(buf.data), buf.metadata
                        finally:
                            buf.release()
                reply = await self._call_owner(
                    ref, "GetObjectStatus",
                    {"id": oid.binary(), "wait_s": 5.0})
                status = reply["status"]
                if status == "inline":
                    return reply["data"], reply["metadata"]
                if status == "error":
                    raise reply["error"]
                if status == "locations":
                    got = await self._fetch_from_locations(
                        oid, reply["locations"], owner=ref.owner_address)
                    if got is not None:
                        return got
                    return None, None
                if status == "unknown":
                    raise ObjectLostError(oid, "owner does not know object")
            # pending → check deadline and loop (owner long-polls internally)
            if owned and st.pending:
                if st.event is None:
                    st.event = asyncio.Event()
                try:
                    wait = None if deadline is None else \
                        deadline - asyncio.get_running_loop().time()
                    if wait is not None and wait <= 0:
                        raise RayTpuTimeoutError(f"get({oid}) timed out")
                    await asyncio.wait_for(st.event.wait(),
                                           None if wait is None else wait)
                except asyncio.TimeoutError:
                    raise RayTpuTimeoutError(f"get({oid}) timed out") from None
            elif deadline is not None and \
                    asyncio.get_running_loop().time() > deadline:
                raise RayTpuTimeoutError(f"get({oid}) timed out")

    async def _call_owner(self, ref: ObjectRef, method: str, req):
        try:
            return await self.pool.get(ref.owner_address).call(
                "CoreWorker", method, req)
        except Exception as e:
            raise ObjectLostError(
                ref.id, f"owner {ref.owner_address} unreachable: {e}") from e

    async def _fetch_from_locations(self, oid: ObjectID, locations,
                                    owner: str | None = None):
        """Pull the object into the local store from any live location
        (reference: object_manager PullManager, locations from the owner —
        OwnershipBasedObjectDirectory)."""
        my_node = self.node_id.hex() if self.node_id else None
        # Local copy?
        if self.store is not None and (my_node in locations):
            buf = self.store.get(oid)
            if buf is not None:
                try:
                    return bytes(buf.data), buf.metadata
                finally:
                    buf.release()
        nodes = await self._node_table()
        # Own node stays in the candidate list: a local store miss with a
        # local location means the object was SPILLED — the hostd restores
        # it from disk through the same pull path.
        for loc in locations:
            addr = nodes.get(loc)
            if addr is None:
                continue
            try:
                fetched = await self._pull_from_node(addr, oid)
            except Exception:
                continue
            if fetched is None:
                continue
            data, metadata = fetched
            if self.store is not None:
                try:
                    if not self.store.contains(oid):
                        self.store.put_bytes(oid, data, metadata)
                    if owner:
                        asyncio.ensure_future(self.pool.get(owner).call(
                            "CoreWorker", "AddLocation",
                            {"id": oid.binary(), "node": my_node}))
                    elif oid in self.objects:
                        self.objects[oid].locations.add(my_node)
                except Exception:
                    pass
            return data, metadata
        return None

    # Chunked node-to-node transfer (reference: object_manager/ chunked
    # push/pull, push_manager.h in-flight chunk throttling).
    PULL_CHUNK_BYTES = 8 << 20
    PULL_MAX_INFLIGHT = 4

    async def _pull_from_node(self, addr: str, oid: ObjectID):
        """Fetch (data, metadata) from one node.  Small objects (the
        common case) cost ONE RPC; past max_inline the daemon answers
        too_large and the payload streams as bounded-concurrency chunks.
        The whole pull is one `object`/`transfer` span (begin -> end with
        mode/bytes), so cross-node data waits show up in critical paths."""
        client = self.pool.get(addr)
        tok = spans.begin("object", "transfer",
                          oid=oid.binary().hex()[:16], src=addr)
        try:
            reply = await client.call(
                "NodeManager", "PullObject",
                {"id": oid.binary(), "max_inline": self.PULL_CHUNK_BYTES})
        except BaseException:
            spans.end(tok, ok=False)
            raise
        if not reply.get("found"):
            spans.end(tok, ok=False)
            return None
        if not reply.get("too_large"):
            spans.end(tok, bytes=len(reply["data"]), mode="inline")
            return reply["data"], reply["metadata"]
        size = reply["data_size"]
        metadata = reply["metadata"]
        # Large payloads ride the native data plane when the remote store
        # serves one (objtransfer.cc): bytes land shm-to-shm with no
        # Python copies.  Any failure falls back to the chunk RPCs below
        # (which also cover spilled objects).
        port = reply.get("transfer_port")
        if port and self.store is not None and self.store_path:
            import socket as _socket

            from ray_tpu._private import object_transfer
            host = addr.rsplit(":", 1)[0]

            def resolve_and_fetch():
                # DNS may block — keep it off the event loop too.
                ip = _socket.gethostbyname(host)
                return object_transfer.fetch(self.store_path, ip, port, oid)

            try:
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, resolve_and_fetch)
            except Exception as e:
                logger.debug("native pull of %s from %s failed: %s",
                             oid, addr, e)
                ok = False
            if ok:
                buf = self.store.get(oid)
                if buf is not None:
                    try:
                        spans.end(tok, bytes=size, mode="native")
                        return bytes(buf.data), buf.metadata
                    finally:
                        buf.release()
        out = bytearray(size)
        sem = asyncio.Semaphore(self.PULL_MAX_INFLIGHT)
        failed = []

        from ray_tpu import protocol

        async def fetch(offset: int):
            length = min(self.PULL_CHUNK_BYTES, size - offset)
            async with sem:
                chunk = await client.call(
                    "NodeManager", "PullObjectChunk",
                    protocol.pb.PullObjectChunkRequest(
                        id=oid.binary(), offset=offset, length=length))
            if not chunk.found:
                failed.append(offset)
                return
            out[offset:offset + length] = chunk.data

        results = await asyncio.gather(
            *[fetch(off) for off in range(0, size, self.PULL_CHUNK_BYTES)],
            return_exceptions=True)
        if failed or any(isinstance(r, BaseException) for r in results):
            spans.end(tok, ok=False)
            return None
        spans.end(tok, bytes=size, mode="chunked")
        return bytes(out), metadata

    _node_cache: tuple | None = None

    async def _node_table(self) -> dict:
        """node_id hex -> hostd address, cached briefly."""
        now = asyncio.get_running_loop().time()
        if self._node_cache is not None and now - self._node_cache[0] < 1.0:
            return self._node_cache[1]
        reply = await self.gcs.call("Gcs", "get_nodes", {})
        table = {n.node_id.hex(): n.address for n in reply["nodes"] if n.alive}
        self._node_cache = (now, table)
        return table

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        rx = getattr(self, "_native_rx", None)
        if rx is not None:   # see get(): never block on held replies
            rx.flush_thread_batch()
        return self.io.run(self._wait_async(refs, num_returns, timeout))

    async def _ready_probe(self, ref: ObjectRef):
        """Block until the object is ready WITHOUT pulling its payload
        (errored objects count as ready, as in the reference)."""
        oid = ref.id
        owned = ref.owner_address in ("", self.address)
        while True:
            if owned:
                st = self.objects.get(oid)
                if st is None:
                    return  # freed/unknown: surfaces as error on get()
                if not st.pending:
                    return
                if st.event is None:
                    st.event = asyncio.Event()
                await st.event.wait()
            else:
                if self.store is not None and self.store.contains(oid):
                    return
                try:
                    reply = await self._call_owner(
                        ref, "GetObjectStatus",
                        {"id": oid.binary(), "wait_s": 5.0})
                except ObjectLostError:
                    return
                if reply["status"] != "pending":
                    return

    async def _wait_async(self, refs, num_returns, timeout):
        pending = {asyncio.ensure_future(self._ready_probe(r)): r
                   for r in refs}
        ready = []
        try:
            deadline = None if timeout is None else \
                asyncio.get_running_loop().time() + timeout
            while pending and len(ready) < num_returns:
                wait_t = None if deadline is None else max(
                    0, deadline - asyncio.get_running_loop().time())
                done, _ = await asyncio.wait(
                    pending.keys(), timeout=wait_t,
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    break
                for f in done:
                    f.exception()  # consume; errored objects count as ready
                    # Cap at num_returns ("at most num_returns" contract):
                    # several probes can complete in one event-loop tick, and
                    # extras must stay in pending, not be silently dropped.
                    if len(ready) < num_returns:
                        ready.append(pending.pop(f))
        finally:
            for f in pending:
                f.cancel()
        return ready, [r for r in refs if r not in ready]

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------

    def submit_task(self, fn, args, kwargs, opts) -> list[ObjectRef]:
        task_id = TaskID.of()
        num_returns = opts.get("num_returns", 1)
        refs = [ObjectRef(ObjectID.for_return(task_id, i), self.address)
                for i in range(num_returns)]
        for ref in refs:
            st = self.objects.setdefault(ref.id, _ObjectState())
            st.producing_task = task_id
        # Fast path: build the spec in the calling thread and hand it to the
        # event loop fire-and-forget.  The blocking io.run round trip (two
        # thread handoffs per submit, ~2.5ms measured) is only needed when
        # something requires the loop: first-time fn export, an uncached
        # runtime_env descriptor, or args big enough to go through the store.
        if not self._launch_sync(fn, args, kwargs, opts, task_id):
            self.io.run(
                self._prepare_and_launch(fn, args, kwargs, opts, task_id))
        return refs

    def _launch_sync(self, fn, args, kwargs, opts, task_id) -> bool:
        fn_key = self.fn_manager.export_cached(fn)
        if fn_key is None:
            return False
        user_env = opts.get("runtime_env")
        renv_desc = {}
        if user_env:
            import json as _json
            renv_desc = self._renv_cache.get(
                _json.dumps(user_env, sort_keys=True, default=str))
            if renv_desc is None:
                return False
        pins: list = []          # applied only if the fast path commits
        packed: list = []

        def pack(value):
            if isinstance(value, ObjectRef):
                pins.append(value)
                return RefArg(value.id.binary(),
                              value.owner_address or self.address)
            sv = ser.serialize(value, ref_sink=pins.append)
            if sv.total_size >= INLINE_LIMIT:
                return None      # store promotion may spill -> loop path
            return ValueArg(sv.to_bytes(), sv.metadata)

        pargs = []
        for a in args:
            p = pack(a)
            if p is None:
                return False
            pargs.append(p)
        pkwargs = {}
        for k, v in kwargs.items():
            p = pack(v)
            if p is None:
                return False
            pkwargs[k] = p
        # Per-options invariants (resources parse, name, retry fields)
        # compute once per RemoteFunction: the opts dict is immutable
        # after validation and identity-stable, and the cache pins it so
        # an id() can never be recycled by a different dict.
        cached = self._opts_cache.get(id(opts))
        if cached is None or cached[0] is not opts:
            cached = (opts, {
                "num_returns": opts.get("num_returns", 1),
                "resources": Resources.from_options(opts),
                "max_retries": opts.get("max_retries", 3),
                "retry_exceptions": bool(opts.get("retry_exceptions",
                                                  False)),
                "scheduling_strategy": (opts.get("scheduling_strategy")
                                        or "DEFAULT"),
                "node_affinity": opts.get("_node_id"),
                "placement_group": _pg_id_of(opts.get("placement_group")),
                "bundle_index": opts.get("placement_group_bundle_index",
                                         -1),
            })
            if len(self._opts_cache) > 4096:
                self._opts_cache.clear()
            self._opts_cache[id(opts)] = cached
        c = cached[1]
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id or JobID.nil(),
            name=getattr(fn, "__qualname__", str(fn)),
            fn_key=fn_key,
            args=pargs,
            kwargs=pkwargs,
            num_returns=c["num_returns"],
            resources=c["resources"],
            max_retries=c["max_retries"],
            retry_exceptions=c["retry_exceptions"],
            owner_address=self.address,
            scheduling_strategy=c["scheduling_strategy"],
            node_affinity=c["node_affinity"],
            placement_group=c["placement_group"],
            bundle_index=c["bundle_index"],
            runtime_env=renv_desc,
        )
        spec.trace_ctx = tracing.current_context()
        # Task-lifecycle spans only exist under an explicit trace: the
        # untraced hot path pays a single None check per site.
        tok_submit = (spans.begin("sched", "submit", ctx=spec.trace_ctx,
                                  name=spec.name)
                      if spec.trace_ctx is not None else None)
        for r in pins:
            self._pin_serialized_ref(r)
        pending = _PendingTask(
            spec=spec, retries_left=spec.max_retries, future=None,
            lineage=True)
        renv_key = id(renv_desc) if user_env else 0
        sk = c.get("_sk")
        if sk is None or sk[0] != renv_key:
            sk = (renv_key, self._sched_key(spec, ()))
            c["_sk"] = sk
        pending.sched_key = sk[1]
        if self._native_on:
            # Pack the native task descriptor off the event loop: dispatch
            # hands it to the C codec (taskrpc.cc tpt_send_specs), which
            # splices it with the per-(fn, opts) template into TaskSpecP
            # wire bytes — no Python serialization of the spec at all.
            tpl = c.get("_tpl_key")
            if tpl is None or tpl[0] != (fn_key, renv_key):
                tpl_bytes = spec_codec.build_template(
                    job_id=spec.job_id.binary(), name=spec.name,
                    fn_key=fn_key, num_returns=c["num_returns"],
                    resources=c["resources"],
                    max_retries=c["max_retries"],
                    retry_exceptions=c["retry_exceptions"],
                    owner_address=self.address,
                    scheduling_strategy=c["scheduling_strategy"],
                    runtime_env=renv_desc)
                # Dedupe by CONTENT: per-call .options() mints a fresh
                # opts dict every submit, and identity-keyed ids would
                # leak a new template into the C registry each time.
                # Distinct contents ~ distinct (fn, options) pairs —
                # bounded in any sane program, like exported fns.
                ent = self._tpl_content.get(tpl_bytes)
                if ent is None:
                    ent = (next(self._tpl_ids), tpl_bytes)
                    self._tpl_content[tpl_bytes] = ent
                tpl = ((fn_key, renv_key), ent)
                c["_tpl_key"] = tpl
            pending.template = tpl[1]
            trace_blob = (_pickle.dumps(spec.trace_ctx, 5)
                          if spec.trace_ctx is not None else None)
            pending.payload = spec_codec.pack_desc(
                tpl[1][0], 0, 0, task_id.binary(), trace_blob,
                pargs, pkwargs)
        self.tasks[task_id] = pending
        # Zero-hop dispatch: a dependency-free task whose scheduling key
        # already holds a lease with a free slot goes to the wire from
        # THIS thread — no event-loop wake on submit (the dominant cost
        # of a sync round trip on a one-core host).
        if pending.payload is not None and not pins and self._native_sub:
            sched = self._lease_cache.get(pending.sched_key)
            if sched is not None and sched.try_direct(pending, spec):
                spans.end(tok_submit, zero_hop=True)
                return True
        if tok_submit is not None:
            # Queue time = enqueue here until a scheduler claims a lease
            # slot in _dispatch; the token rides on the pending task.
            pending.q_span = spans.begin("sched", "sched_queue",
                                         ctx=spec.trace_ctx,
                                         name=spec.name)
        self._enqueue_fast(("task", task_id))
        spans.end(tok_submit)
        return True

    def _enqueue_fast(self, item):
        """Queue a loop-side dispatch, waking the loop once per burst (the
        GIL makes the flag check/append atomic enough: the drain clears
        the flag BEFORE popping, so late appends re-schedule)."""
        self._fast_q.append(item)
        if not self._fast_scheduled:
            self._fast_scheduled = True
            self.io.loop.call_soon_threadsafe(self._drain_fast)

    def _drain_fast(self):
        self._fast_scheduled = False
        q = self._fast_q
        # ONE shared per-worker batch for the whole burst — actor pushes
        # AND normal-task dispatches coalesce into one library call per
        # worker (a per-_pump dict would flush single-payload batches).
        batches: dict = {}   # native addr -> [(payload, cb)]
        while q:
            kind, *rest = q.popleft()
            if kind == "task":
                self._fast_submit(rest[0], batches=batches)
            else:
                self._fast_submit_actor(*rest, batches=batches)
        if not batches:
            return
        if self._batch_wait_s > 0:
            # Park this burst's batches in the tick dict: more
            # submissions arriving within the window append to the same
            # per-worker vectors and ship in ONE call_spec_batch.
            tb = self._tick_batches
            for naddr, items in batches.items():
                tb.setdefault(naddr, []).extend(items)
            if not self._tick_flush_scheduled:
                self._tick_flush_scheduled = True
                self.io.loop.call_later(self._batch_wait_s,
                                        self._flush_tick_batches)
            return
        for naddr, items in batches.items():
            self._ship_batch(naddr, items)

    def _ship_batch(self, naddr, items):
        """Flush one per-worker dispatch batch.  Items carry an optional
        `sched/dispatch` span token in slot 3: the span covers dispatch
        DECISION through this ship (the actual scheduler work); the
        residency tail — shipped until the push completes — is a
        separate `sched/inflight` span closed by each completion
        callback, so pipelined waiting is never booked as dispatch."""
        self._native_sub.call_spec_batch(
            naddr, [(p, t, cb) for p, t, cb, _tok in items])
        for _p, _t, _cb, tok in items:
            if tok is not None:
                spans.end(tok)

    def _shared_batches(self) -> dict:
        """Per-loop-tick native dispatch batch: every _pump triggered
        inside one completion batch appends here, and ONE call_soon'd
        flush ships a single call_spec_batch per worker.  Without this,
        each completion's pump dispatched 1-3 tasks in its own library
        call (measured: 1,373 batches for 4,000 tasks)."""
        if not self._tick_flush_scheduled:
            self._tick_flush_scheduled = True
            self.io.loop.call_soon(self._flush_tick_batches)
        return self._tick_batches

    def _flush_tick_batches(self):
        self._tick_flush_scheduled = False
        b = self._tick_batches
        if not b:
            return
        self._tick_batches = {}
        if not self._native_sub:
            return
        for naddr, items in b.items():
            self._ship_batch(naddr, items)

    def _pending_dep_events(self, spec: TaskSpec) -> list:
        """asyncio.Events for this task's UNRESOLVED owned dependencies.

        Dependency gating (reference: raylet dependency manager,
        task_dependency_manager.h — a task is not dispatched until its
        args are available): normal tasks execute INLINE in per-worker
        FIFO order, so a task pushed ahead of its not-yet-finished
        producer would block the worker its producer needs — a
        head-of-line deadlock when both land on one worker.  Holding
        dispatch until owned deps complete makes the order safe by
        construction.  Borrowed refs (owner elsewhere) stay eager: their
        producers were submitted by another owner, so no local FIFO
        ordering exists to violate, and the worker-side poll makes
        progress independently."""
        evs = []
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if not isinstance(arg, RefArg):
                continue
            st = self.objects.get(ObjectID(arg.id_binary))
            if st is not None and st.pending:
                if st.event is None:
                    st.event = asyncio.Event()
                evs.append((ObjectID(arg.id_binary), st))
        return evs

    async def _submit_after_deps(self, task_id, deps):
        await self._await_deps(deps)
        self._fast_submit(task_id)

    async def _await_deps(self, deps) -> None:
        for _oid, st in deps:
            while st.pending:
                ev = st.event
                if ev is None:
                    ev = st.event = asyncio.Event()
                try:
                    # Bounded wait: lineage reconstruction replaces the
                    # event object, so re-read it instead of blocking on
                    # a stale one forever.
                    await asyncio.wait_for(ev.wait(), 1.0)
                except asyncio.TimeoutError:
                    pass

    def _fast_submit(self, task_id, batches=None):
        """Loop-side entry for fast-path tasks: enqueue on the scheduling-
        key scheduler with a direct-completion sink (no coroutine, no
        future).  Placement/affinity strategies take the coroutine path.
        With `batches`, dispatches accumulate for the caller's one-call-
        per-worker flush (_drain_fast)."""
        pending = self.tasks.get(task_id)
        if pending is None:
            return
        spec = pending.spec
        deps = self._pending_dep_events(spec)
        if deps:
            asyncio.ensure_future(self._submit_after_deps(task_id, deps))
            return
        if (spec.placement_group is not None
                or spec.scheduling_strategy not in (None, "DEFAULT")
                or spec.node_affinity):
            asyncio.ensure_future(self._run_task_to_completion(task_id))
            return
        key = pending.sched_key
        if key is None:
            key = self._sched_key(spec, ())
        sched = self._lease_cache.get(key)
        if sched is None:
            sched = self._lease_cache[key] = _KeyScheduler(
                self, key, spec, [])
        sched.submit_nowait(spec, batches=batches)

    async def _resume_task_fast(self, task_id: TaskID, exc):
        """Apply one failure outcome to a fast-path task, then continue in
        the standard retry loop (mirrors _run_task_to_completion's except
        arms; exc None = app error under retry_exceptions)."""
        from ray_tpu.exceptions import TaskCancelledError
        pending = self.tasks.get(task_id)
        if pending is None:
            return
        spec = pending.spec
        if pending.cancelled:
            self._complete_task_error(
                spec, TaskCancelledError(f"task {spec.name} cancelled"))
            return
        if exc is None:
            pending.retries_left -= 1
            await self._run_task_to_completion(task_id, exclusive=True)
        elif isinstance(exc, _RetryableSubmitError):
            if exc.busy:
                await asyncio.sleep(0.1)
                await self._run_task_to_completion(task_id)
            elif pending.retries_left > 0:
                pending.retries_left -= 1
                await self._run_task_to_completion(task_id, exclusive=True)
            else:
                self._complete_task_error(
                    spec, WorkerCrashedError(f"task {spec.name}: {exc}"))
        else:
            self._complete_task_error(spec, exc)

    async def _build_runtime_env(self, user_env) -> dict:
        """Package a user runtime_env once per unique value (content-
        addressed uploads make repeats cheap anyway)."""
        if not user_env:
            return {}
        import json as _json

        from ray_tpu._private import runtime_env as renv
        cache_key = _json.dumps(user_env, sort_keys=True, default=str)
        cached = self._renv_cache.get(cache_key)
        if cached is None:
            cached = await renv.build_descriptor(user_env, self._kv_call)
            self._renv_cache[cache_key] = cached
        return cached

    async def _prepare_and_launch(self, fn, args, kwargs, opts, task_id):
        fn_key = await self.fn_manager.export(self._job_int(), fn)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id or JobID.nil(),
            name=getattr(fn, "__qualname__", str(fn)),
            fn_key=fn_key,
            args=[await self._pack_arg(a) for a in args],
            kwargs={k: await self._pack_arg(v) for k, v in kwargs.items()},
            num_returns=opts.get("num_returns", 1),
            resources=Resources.from_options(opts),
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            owner_address=self.address,
            scheduling_strategy=opts.get("scheduling_strategy") or "DEFAULT",
            node_affinity=opts.get("_node_id"),
            placement_group=_pg_id_of(opts.get("placement_group")),
            bundle_index=opts.get("placement_group_bundle_index", -1),
            runtime_env=await self._build_runtime_env(
                opts.get("runtime_env")),
        )
        spec.trace_ctx = tracing.current_context()
        self.tasks[task_id] = _PendingTask(
            spec=spec, retries_left=spec.max_retries, future=None, lineage=True)
        asyncio.ensure_future(self._run_task_to_completion(task_id))

    def _job_int(self) -> int:
        return int.from_bytes((self.job_id or JobID.nil()).binary(), "little")

    async def _pack_arg(self, value):
        if isinstance(value, ObjectRef):
            self._pin_serialized_ref(value)
            return RefArg(value.id.binary(), value.owner_address or self.address)
        sv = ser.serialize(value, ref_sink=self._pin_serialized_ref)
        if sv.total_size >= INLINE_LIMIT:
            # Promote big args to the object store (reference: args >100KB go
            # through plasma, _raylet.pyx submit_task).
            oid = ObjectID.for_put(self.current_task_id, self._next_put_index())
            try:
                self._store_owned_value(oid, sv)
            except ObjectStoreFullError:
                for attempt in range(3):
                    freed = await self._request_spill(sv.total_size)
                    try:
                        self._store_owned_value(oid, sv)
                        break
                    except ObjectStoreFullError:
                        if not freed:
                            await asyncio.sleep(0.2)
                else:
                    self._store_owned_value(oid, sv)
            st = self.objects[oid]
            st.pins += 1
            return RefArg(oid.binary(), self.address)
        return ValueArg(sv.to_bytes(), sv.metadata)

    def cancel_task(self, ref: ObjectRef, force: bool = False,
                    recursive: bool = True):
        """Cancel the task producing `ref` (reference: worker.py
        ray.cancel:2793 + core_worker.proto CancelTask:433)."""
        st = self.objects.get(ref.id)
        if st is None or st.producing_task is None:
            raise ValueError(
                "ray_tpu.cancel() only supports task returns; use "
                "ray_tpu.kill() for actors")
        pending = self.tasks.get(st.producing_task)
        if pending is None or not st.pending:
            return  # already finished
        pending.cancelled = True
        self.io.run(self._cancel_pending(pending, force), timeout=15)

    async def _cancel_pending(self, pending: _PendingTask, force: bool):
        from ray_tpu.exceptions import TaskCancelledError
        task_id = pending.spec.task_id
        # Still queued client-side: drop it from its key scheduler.
        for sched in list(self._lease_cache.values()):
            for item in list(sched.queue):
                spec, fut, _excl = item
                if spec.task_id == task_id:
                    try:
                        sched.queue.remove(item)
                    except ValueError:
                        continue
                    exc = TaskCancelledError(f"task {spec.name} cancelled")
                    if fut is None:
                        self._complete_task_error(spec, exc)
                    elif not fut.done():
                        fut.set_exception(exc)
                    sched._maybe_gc()
                    return
        # Already pushed: cancel at the executing worker.
        if pending.worker_address:
            try:
                await self.pool.get(pending.worker_address).call(
                    "CoreWorker", "CancelTask",
                    {"task_id": task_id.binary(), "force": force},
                    timeout=10)
            except Exception:
                pass

    async def _run_task_to_completion(self, task_id: TaskID,
                                      exclusive: bool = False):
        from ray_tpu.exceptions import TaskCancelledError
        pending = self.tasks.get(task_id)
        spec = pending.spec
        # Dependency gate (see _pending_dep_events): never push a task
        # ahead of its unfinished producer.
        await self._await_deps(self._pending_dep_events(spec))
        exclude: list = []
        # Resubmissions dispatch exclusively (see _KeyScheduler._pump's
        # dependency-safety sketch).
        while True:
            if pending.cancelled:
                self._complete_task_error(
                    spec, TaskCancelledError(f"task {spec.name} cancelled"))
                return
            try:
                reply = await self._submit_once(spec, exclude,
                                                exclusive=exclusive)
            except TaskCancelledError as e:
                self._complete_task_error(spec, e)
                return
            except _RetryableSubmitError as e:
                if pending.cancelled:
                    self._complete_task_error(
                        spec,
                        TaskCancelledError(f"task {spec.name} cancelled"))
                    return
                if e.busy:
                    # Saturated cluster: keep queueing, don't burn retries
                    # (the reference queues tasks in the raylet indefinitely).
                    exclude.clear()
                    await asyncio.sleep(0.1)
                    continue
                if pending.retries_left > 0:
                    pending.retries_left -= 1
                    exclusive = True
                    if e.node_id is not None:
                        exclude.append(e.node_id)
                    logger.info("retrying task %s (%s left): %s", spec.name,
                                pending.retries_left, e)
                    continue
                self._complete_task_error(
                    spec, WorkerCrashedError(f"task {spec.name}: {e}"))
                return
            except Exception as e:  # scheduling errors etc.
                self._complete_task_error(spec, e)
                return
            err = reply.get("error")
            if err is not None and spec.retry_exceptions \
                    and pending.retries_left > 0 \
                    and not pending.cancelled \
                    and not isinstance(err, TaskCancelledError):
                pending.retries_left -= 1
                continue
            self._complete_task_reply(spec, reply)
            return

    def _sched_key(self, spec: TaskSpec, exclude) -> tuple:
        """Reference SchedulingKey (direct_task_transport.h:53-55):
        tasks with identical scheduling requirements share leases."""
        from ray_tpu._private import runtime_env as renv
        return (tuple(sorted(spec.resources.to_dict().items())),
                spec.scheduling_strategy,
                spec.placement_group.hex() if spec.placement_group else None,
                spec.bundle_index, spec.node_affinity, tuple(exclude),
                renv.env_hash(spec.runtime_env))

    async def _push_on_lease(self, spec: TaskSpec, lease: dict):
        addr = lease["worker_address"]
        reply = await self._native_call_worker(addr, spec)
        if reply is None:  # peer (or self) has no native plane
            req = {"spec": spec, "caller": self.worker_id.binary()}
            reply = await self.pool.get(addr).call(
                "CoreWorker", "PushTask", req, timeout=None)
        return reply

    async def _return_lease(self, lease: dict, kill: bool = False):
        try:
            await self.pool.get(lease["node_address"]).call(
                "NodeManager", "ReturnWorker",
                {"lease_id": lease["lease_id"], "kill": kill}, timeout=5)
        except Exception:
            pass

    async def _drain_leases(self):
        scheds = list(self._lease_cache.values())
        self._lease_cache.clear()
        for sched in scheds:
            await sched.drain()

    async def _submit_once(self, spec: TaskSpec, exclude,
                           exclusive: bool = False):
        """Queue the task under its scheduling key; the per-key scheduler
        pipelines queued tasks onto held worker leases (reference:
        direct_task_transport.h OnWorkerIdle:151, lease request rate
        limiting :59)."""
        key = self._sched_key(spec, exclude)
        sched = self._lease_cache.get(key)
        if sched is None:
            sched = self._lease_cache[key] = _KeyScheduler(
                self, key, spec, list(exclude))
        return await sched.submit(spec, exclusive=exclusive)

    async def _resolve_bundle(self, spec: TaskSpec):
        """Map (placement_group, bundle_index) to the bundle's node + lease
        bundle key, waiting for the PG to finish scheduling."""
        reply = await self.gcs.call(
            "Gcs", "get_placement_group",
            {"pg_id": spec.placement_group, "wait_s": 30})
        info = reply.get("info")
        if info is None or info.state == "REMOVED":
            raise ValueError(
                f"placement group {spec.placement_group.hex()[:8]} is "
                f"{'missing' if info is None else 'removed'}")
        if info.state != "CREATED":
            raise _RetryableSubmitError("placement group not ready",
                                        None, busy=True)
        demand = spec.resources.to_dict()

        def bundle_fits(b: dict) -> bool:
            return all(b.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items() if v > 0)

        idx = spec.bundle_index
        if idx < 0:
            # Any bundle whose RESERVATION can fit the demand; rotate for
            # balance.  No bundle large enough = permanent infeasibility.
            feasible = [i for i, b in enumerate(info.bundles)
                        if bundle_fits(b)]
            if not feasible:
                raise ValueError(
                    f"task {spec.name} demands {demand}, which exceeds "
                    f"every bundle of placement group "
                    f"{spec.placement_group.hex()[:8]}")
            rr = self._pg_rr.get(spec.placement_group, 0)
            idx = feasible[rr % len(feasible)]
            self._pg_rr[spec.placement_group] = rr + 1
        elif idx >= len(info.bundles):
            raise ValueError(f"bundle index {idx} out of range "
                             f"({len(info.bundles)} bundles)")
        elif not bundle_fits(info.bundles[idx]):
            raise ValueError(
                f"task {spec.name} demands {demand}, which exceeds bundle "
                f"{idx} ({info.bundles[idx]}) of placement group "
                f"{spec.placement_group.hex()[:8]}")
        # The PG record already carries the bundle's node and address — no
        # extra get_nodes round-trip; a dead node surfaces as a failed
        # lease RPC, which is retryable anyway.
        node_id, address = info.bundle_nodes[idx], info.bundle_addresses[idx]
        if node_id is None or not address:
            raise _RetryableSubmitError("bundle unplaced", None, busy=True)
        node = _BundleNode(address=address, node_id=node_id)
        return node, (spec.placement_group.hex(), idx)

    def _complete_task_reply(self, spec: TaskSpec, reply):
        returns = reply.get("returns", [])
        err = reply.get("error")
        for i in range(spec.num_returns):
            oid = ObjectID.for_return(spec.task_id, i)
            st = self.objects.setdefault(oid, _ObjectState())
            if err is not None:
                st.error = err
            else:
                kind, payload, meta = returns[i]
                if kind == "inline":
                    st.inline = (payload, meta)
                else:  # "location"
                    st.locations.add(payload)
            with self._obj_lock:
                st.pending = False   # publish flag: set last (see get())
            self._signal_ready(oid, st)
        self._release_arg_pins(spec)

    def _complete_task_error(self, spec: TaskSpec, exc: BaseException):
        for i in range(spec.num_returns):
            oid = ObjectID.for_return(spec.task_id, i)
            st = self.objects.setdefault(oid, _ObjectState())
            st.error = exc
            with self._obj_lock:
                st.pending = False   # publish flag: set last (see get())
            self._signal_ready(oid, st)
        self._release_arg_pins(spec)

    def _release_arg_pins(self, spec: TaskSpec):
        if not spec.args and not spec.kwargs:
            return
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(arg, RefArg):
                oid = ObjectID(arg.id_binary)
                with self._obj_lock:
                    st = self.objects.get(oid)
                    if st is not None:
                        st.pins = max(0, st.pins - 1)
                if st is not None:
                    self._maybe_free(oid)
                elif arg.owner_address not in ("", self.address):
                    asyncio.ensure_future(
                        self.pool.get(arg.owner_address).call(
                            "CoreWorker", "RemoveBorrow",
                            {"id": arg.id_binary}))

    async def _try_reconstruct(self, ref: ObjectRef) -> bool:
        """Lineage reconstruction: resubmit the producing task
        (reference: object_recovery_manager.h:41).

        Retry accounting: exactly ONE retry is burned per lost-output
        event regardless of how many getters notice — concurrent getters
        (and getters of sibling returns of the same task) piggyback on
        the in-flight resubmission via `_reconstructing` instead of each
        decrementing `retries_left` and racing duplicate resubmits."""
        st = self.objects.get(ref.id)
        if st is None or st.producing_task is None:
            return False
        tid = st.producing_task
        inflight = self._reconstructing.get(tid)
        if inflight is not None:
            await inflight.wait()
            return True
        pending = self.tasks.get(tid)
        if pending is None or pending.retries_left <= 0:
            return False
        pending.retries_left -= 1
        done = asyncio.Event()
        self._reconstructing[tid] = done
        try:
            for i in range(pending.spec.num_returns):
                oid = ObjectID.for_return(pending.spec.task_id, i)
                rst = self.objects.setdefault(oid, _ObjectState())
                rst.pending = True
                rst.inline = None
                rst.error = None
                rst.locations.clear()
                rst.event = asyncio.Event()
            logger.info("reconstructing %s via task %s", ref.id,
                        pending.spec.name)
            await self._run_task_to_completion(tid)
        finally:
            self._reconstructing.pop(tid, None)
            done.set()
        return True

    # ------------------------------------------------------------------
    # Actors
    # ------------------------------------------------------------------

    def create_actor(self, cls, args, kwargs, opts) -> ActorID:
        actor_id = ActorID.of(self.job_id or JobID.nil())
        if opts.get("name") or opts.get("get_if_exists"):
            # Named actors need the registration reply (it may resolve to
            # an existing actor's id).
            return self.io.run(
                self._create_actor_async(actor_id, cls, args, kwargs, opts))
        # Anonymous actors register ASYNCHRONOUSLY (reference:
        # core_worker actor creation is non-blocking; an actor storm must
        # pipeline registrations, not serialize on one GCS round trip per
        # handle).  The handle is immediately usable: method submission
        # waits in _resolve_actor while the id is in _pending_actor_reg.
        self._pending_actor_reg.add(actor_id)
        asyncio.run_coroutine_threadsafe(
            self._register_actor_bg(actor_id, cls, args, kwargs, opts),
            self.io.loop)
        return actor_id

    async def _register_actor_bg(self, actor_id, cls, args, kwargs, opts):
        try:
            await self._create_actor_async(actor_id, cls, args, kwargs,
                                           opts)
        except Exception:
            # Surfaces as ActorDiedError("unknown actor") at first use.
            logger.exception("background actor registration failed")
        finally:
            self._pending_actor_reg.discard(actor_id)

    async def _create_actor_async(self, actor_id, cls, args, kwargs, opts):
        from ray_tpu._private.protocol import ActorInfo
        fn_key = await self.fn_manager.export(self._job_int(), cls)
        spec = TaskSpec(
            task_id=TaskID.of(actor_id),
            job_id=self.job_id or JobID.nil(),
            name=f"{cls.__name__}.__init__",
            fn_key=fn_key,
            args=[await self._pack_arg(a) for a in args],
            kwargs={k: await self._pack_arg(v) for k, v in kwargs.items()},
            # Reference semantics: a default actor takes 1 CPU for scheduling
            # but 0 while running, so resident actors don't starve tasks.
            resources=Resources.from_options(opts, default_cpu=0.0),
            owner_address=self.address,
            actor_id=actor_id,
            actor_creation=True,
            max_concurrency=opts.get("max_concurrency") or 0,
            placement_group=_pg_id_of(opts.get("placement_group")),
            bundle_index=opts.get("placement_group_bundle_index", -1),
            runtime_env=await self._build_runtime_env(
                opts.get("runtime_env")),
        )
        spec.trace_ctx = tracing.current_context()
        info = ActorInfo(
            actor_id=actor_id,
            name=opts.get("name") or "",
            namespace=opts.get("namespace") or "default",
            class_name=cls.__name__,
            owner_address=self.address,
            max_restarts=opts.get("max_restarts", 0) or 0,
            lifetime_detached=(opts.get("lifetime") == "detached"),
            creation_spec=spec,
            resources=Resources.from_options(opts, default_cpu=0.0),
        )
        reply = await self.gcs.call(
            "Gcs", "register_actor",
            {"info": info, "get_if_exists": opts.get("get_if_exists", False)})
        if reply.get("existing") is not None:
            return reply["existing"].actor_id
        return actor_id

    # ------------------------------------------------------------------
    # Placement groups (client side)
    # ------------------------------------------------------------------

    def create_placement_group(self, bundles, strategy="PACK", name="",
                               lifetime=None):
        from ray_tpu._private.ids import PlacementGroupID
        from ray_tpu._private.protocol import PlacementGroupInfo
        pg_id = PlacementGroupID.from_random()
        info = PlacementGroupInfo(
            pg_id=pg_id, bundles=list(bundles), strategy=strategy, name=name,
            creator_job=self._job_int(),
            lifetime_detached=(lifetime == "detached"))
        self.io.run(self.gcs.call("Gcs", "create_placement_group",
                                  {"info": info}))
        return pg_id

    def wait_placement_group_ready(self, pg_id, timeout: float | None):
        deadline = None if timeout is None else timeout
        reply = self.io.run(self.gcs.call(
            "Gcs", "get_placement_group",
            {"pg_id": pg_id, "wait_s": 3600 if deadline is None else deadline}))
        info = reply.get("info")
        return info is not None and info.state == "CREATED"

    def get_placement_group_info(self, pg_id):
        return self.io.run(self.gcs.call(
            "Gcs", "get_placement_group", {"pg_id": pg_id}))["info"]

    def remove_placement_group(self, pg_id):
        self.io.run(self.gcs.call("Gcs", "remove_placement_group",
                                  {"pg_id": pg_id}))

    def list_placement_groups(self):
        return self.io.run(self.gcs.call(
            "Gcs", "list_placement_groups", {}))["placement_groups"]

    def _get_submitter(self, actor_id: ActorID) -> "_ActorSubmitter":
        sub = self.actor_submitters.get(actor_id)
        if sub is None:
            with self._obj_lock:
                sub = self.actor_submitters.setdefault(
                    actor_id, _ActorSubmitter(actor_id))
        return sub

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args,
                          kwargs, opts) -> list[ObjectRef]:
        task_id = TaskID.of(actor_id)
        num_returns = opts.get("num_returns", 1)
        refs = [ObjectRef(ObjectID.for_return(task_id, i), self.address)
                for i in range(num_returns)]
        # Sequence numbers are claimed HERE, in the submitting thread, so
        # program order == seq order regardless of which path (sync fast /
        # loop slow) finishes building the spec first.
        sub = self._get_submitter(actor_id)
        with sub.lock:
            seq_no = sub.seq
            sub.seq += 1
        if not self._launch_actor_sync(sub, method_name, args, kwargs, opts,
                                       task_id, seq_no):
            self.io.run(self._prep_actor_task(sub, method_name, args, kwargs,
                                              opts, task_id, seq_no))
        return refs

    def _actor_spec(self, sub, method_name, packed_args, packed_kwargs,
                    opts, task_id, seq_no) -> TaskSpec:
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id or JobID.nil(),
            name=method_name,
            fn_key="",
            args=packed_args,
            kwargs=packed_kwargs,
            num_returns=opts.get("num_returns", 1),
            owner_address=self.address,
            actor_id=sub.actor_id,
            method_name=method_name,
            max_retries=opts.get("max_task_retries", 0),
        )
        spec.seq_no = seq_no
        spec.trace_ctx = tracing.current_context()
        return spec

    def _launch_actor_sync(self, sub, method_name, args, kwargs, opts,
                           task_id, seq_no) -> bool:
        """Caller-thread actor submission fast path (mirrors
        _launch_sync)."""
        pins: list = []

        def pack(value):
            if isinstance(value, ObjectRef):
                pins.append(value)
                return RefArg(value.id.binary(),
                              value.owner_address or self.address)
            sv = ser.serialize(value, ref_sink=pins.append)
            if sv.total_size >= INLINE_LIMIT:
                return None
            return ValueArg(sv.to_bytes(), sv.metadata)

        pargs = []
        for a in args:
            p = pack(a)
            if p is None:
                return False
            pargs.append(p)
        pkwargs = {}
        for k, v in kwargs.items():
            p = pack(v)
            if p is None:
                return False
            pkwargs[k] = p
        spec = self._actor_spec(sub, method_name, pargs, pkwargs, opts,
                                task_id, seq_no)
        for r in pins:
            self._pin_serialized_ref(r)
        pending = _PendingTask(
            spec=spec, retries_left=spec.max_retries, future=None)
        if self._native_on:
            with sub.lock:
                epoch_base = sub.epoch_base
            nret = spec.num_returns
            mret = spec.max_retries
            tpl = sub.tpl_cache.get((method_name, nret, mret))
            if tpl is None:
                tpl_bytes = spec_codec.build_template(
                    job_id=spec.job_id.binary(), name=method_name,
                    fn_key="", num_returns=nret,
                    resources=spec.resources, max_retries=mret,
                    retry_exceptions=False, owner_address=self.address,
                    actor_id=sub.actor_id.binary(),
                    method_name=method_name)
                tpl = (next(self._tpl_ids), tpl_bytes)
                sub.tpl_cache[(method_name, nret, mret)] = tpl
            pending.template = tpl
            trace_blob = (_pickle.dumps(spec.trace_ctx, 5)
                          if spec.trace_ctx is not None else None)
            pending.payload = spec_codec.pack_desc(
                tpl[0], seq_no, seq_no - epoch_base, task_id.binary(),
                trace_blob, pargs, pkwargs)
            pending.payload_epoch_base = epoch_base
        self.tasks[task_id] = pending
        self._enqueue_fast(("actor", sub, task_id))
        return True

    def _fast_submit_actor(self, sub, task_id, batches):
        """Loop-side actor dispatch: straight onto the native plane when
        the actor's address and native route are already known.  With
        `batches`, the push is accumulated for a one-call-per-worker
        flush by the caller (_drain_fast)."""
        pending = self.tasks.get(task_id)
        if pending is None:
            return
        addr = sub.address
        if (addr and pending.payload is not None and self._native_sub
                and pending.payload_epoch_base == sub.epoch_base):
            # The epoch check guards a submit-time-baked wire seq: a
            # restart detected between payload build and this dispatch
            # rebases epoch_base, and a stale (too-large) wire seq could
            # collide in the receiver's held window.  Rebased tasks take
            # the slow path, which computes the seq fresh per attempt.
            naddr = self._native_addrs.get(addr)
            if naddr:
                # Always batched: the only caller is _drain_fast, which
                # owns the burst's per-worker batch dict and flushes it.
                # Capture the incarnation now: by the time a failure
                # callback fires the submitter may point at a restart.
                ver = sub.version
                cb = (lambda status, data: self._on_actor_push_done(
                    sub, task_id, addr, status, data, ver))
                batches.setdefault(naddr, []).append(
                    (pending.payload, pending.template, cb, None))
                return
        asyncio.ensure_future(self._run_actor_task(sub, task_id))

    def _on_actor_push_done(self, sub, task_id, addr, status, data,
                            version: int = -1):
        pending = self.tasks.get(task_id)
        if pending is None:
            return
        spec = pending.spec
        if status == 0:
            try:
                reply = spec_codec.reply_from_wire(data)
            except BaseException as e:  # noqa: BLE001
                self._complete_task_error(spec, e)
                return
            sub.completed += 1
            self._complete_task_reply(spec, reply)
            return
        from ray_tpu._private.task_transport import ConnClosedError
        asyncio.ensure_future(
            self._actor_push_failed_cont(
                sub, task_id, addr,
                ConnClosedError("native connection closed"), version))

    async def _actor_push_failed_cont(self, sub, task_id, addr, exc,
                                      version: int = -1):
        pending = self.tasks.get(task_id)
        if pending is None:
            return
        if await self._actor_failure_step(sub, pending, pending.spec, addr,
                                          exc, version):
            return
        await self._run_actor_task(sub, task_id)

    async def _prep_actor_task(self, sub, method_name, args, kwargs,
                               opts, task_id, seq_no):
        spec = self._actor_spec(
            sub, method_name,
            [await self._pack_arg(a) for a in args],
            {k: await self._pack_arg(v) for k, v in kwargs.items()},
            opts, task_id, seq_no)
        self.tasks[task_id] = _PendingTask(
            spec=spec, retries_left=spec.max_retries, future=None)
        asyncio.ensure_future(self._run_actor_task(sub, task_id))

    async def _run_actor_task(self, sub: _ActorSubmitter, task_id: TaskID):
        pending = self.tasks[task_id]
        spec = pending.spec
        while True:
            try:
                addr = await self._resolve_actor(sub)
            except ActorDiedError as e:
                self._complete_task_error(spec, e)
                return
            ver = sub.version   # incarnation this dispatch targets
            try:
                reply = await self._native_call_worker(
                    addr, spec, wire_seq=spec.seq_no - sub.epoch_base)
                if reply is None:
                    req = {"spec": spec, "caller": self.worker_id.binary(),
                           "seq": spec.seq_no - sub.epoch_base}
                    reply = await self.pool.get(addr).call(
                        "CoreWorker", "PushTask", req, timeout=None)
                sub.completed += 1
                self._complete_task_reply(spec, reply)
                return
            except Exception as e:
                if await self._actor_failure_step(sub, pending, spec,
                                                  addr, e, ver):
                    return

    async def _actor_failure_step(self, sub, pending, spec, addr,
                                  e, version: int = -1) -> bool:
        """One transport-failure outcome for an actor call; True = the task
        completed terminally (with an error).

        `version` is the actor incarnation the caller OBSERVED when it
        dispatched (captured at resolve time).  The rebase below must run
        once per incarnation death: without the version guard, a stale
        failure callback arriving after the actor restarted on a reused
        address would rebase a LIVE incarnation's window and desequence
        every in-flight call."""
        self.pool.invalidate(addr)
        with sub.lock:
            if sub.address == addr and (version < 0
                                        or sub.version == version):
                # First detector of this incarnation's death: rebase
                # the wire sequence for the next incarnation.
                sub.address = None
                sub.epoch_base = sub.completed
        if pending.retries_left != 0:
            if pending.retries_left > 0:
                pending.retries_left -= 1
            await asyncio.sleep(0.1)
            return False
        # Terminal failure of an undelivered call: its wire slot on
        # the new incarnation will never be filled, so shift the
        # window or every later call would be held forever.
        with sub.lock:
            sub.completed += 1
            sub.epoch_base += 1
        self._complete_task_error(
            spec, ActorDiedError(sub.actor_id, f"call failed: {e}"))
        return True

    async def _resolve_actor(self, sub: _ActorSubmitter) -> str:
        if sub.address:
            return sub.address
        # Reference semantics: calls on a PENDING actor wait for it (a
        # storm's last actors can legitimately take minutes to schedule
        # on a saturated cluster); the cap only guards true losses.
        deadline = asyncio.get_running_loop().time() + 600
        while asyncio.get_running_loop().time() < deadline:
            reply = await self.gcs.call(
                "Gcs", "get_actor_info",
                {"actor_id": sub.actor_id, "wait_s": 5.0})
            info = reply["info"]
            if info is None:
                if sub.actor_id in self._pending_actor_reg:
                    # Our own registration is still in flight.
                    await asyncio.sleep(0.02)
                    continue
                raise ActorDiedError(sub.actor_id, "unknown actor")
            if info.state == "ALIVE":
                sub.address = info.address
                sub.version = info.version
                port = getattr(info, "native_port", 0)
                if port and info.address not in self._native_addrs:
                    # The actor record carries the native route: skip the
                    # per-worker NativePort discovery RPC.
                    self._native_addrs[info.address] = (
                        f"{info.address.rsplit(':', 1)[0]}:{port}")
                return info.address
            if info.state == "DEAD":
                raise ActorDiedError(sub.actor_id, info.death_cause)
            await asyncio.sleep(0.1)
        raise ActorDiedError(sub.actor_id, "timed out waiting for actor")

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.io.run(self.gcs.call("Gcs", "kill_actor",
                                  {"actor_id": actor_id,
                                   "no_restart": no_restart}))

    def get_named_actor(self, name: str, namespace: str = "default"):
        reply = self.io.run(self.gcs.call(
            "Gcs", "get_named_actor", {"name": name, "namespace": namespace}))
        return reply["info"]

    # ------------------------------------------------------------------
    # Reference counting (owner side)
    # ------------------------------------------------------------------

    def _pin_serialized_ref(self, ref: ObjectRef):
        if ref.owner_address in ("", self.address):
            with self._obj_lock:
                st = self.objects.get(ref.id)
                if st is not None:
                    st.pins += 1
        else:
            self.io.spawn(self.pool.get(ref.owner_address).call(
                "CoreWorker", "AddBorrow", {"id": ref.id.binary()}))

    def on_ref_created(self, ref: ObjectRef):
        if ref.owner_address in ("", self.address):
            with self._obj_lock:
                st = self.objects.setdefault(ref.id, _ObjectState())
                st.local_refs += 1

    def on_ref_deleted(self, ref: ObjectRef):
        if self._shutdown:
            return
        if ref.owner_address in ("", self.address):
            with self._obj_lock:
                st = self.objects.get(ref.id)
                if st is not None:
                    st.local_refs = max(0, st.local_refs - 1)
            self._maybe_free(ref.id)
        else:
            owner = self.borrowed.pop(ref.id, None)
            if owner:
                try:
                    self.io.spawn(self.pool.get(owner).call(
                        "CoreWorker", "RemoveBorrow", {"id": ref.id.binary()}))
                except Exception:
                    pass

    def on_ref_deserialized(self, ref: ObjectRef):
        if ref.owner_address not in ("", self.address):
            self.borrowed[ref.id] = ref.owner_address
            try:
                self.io.spawn(self.pool.get(ref.owner_address).call(
                    "CoreWorker", "AddBorrow", {"id": ref.id.binary()}))
            except Exception:
                pass

    def _maybe_free(self, oid: ObjectID):
        with self._obj_lock:
            st = self.objects.get(oid)
            if st is None or st.pending:
                return
            if st.local_refs > 0 or st.borrows > 0 or st.pins > 0:
                return
            self.objects.pop(oid, None)
        if st.locations:
            self.io.spawn(self._free_locations(oid, set(st.locations)))
        self.tasks.pop(st.producing_task, None)

    async def _free_locations(self, oid: ObjectID, locations):
        """Buffer frees and flush batched (one FreeObjects RPC per node per
        flush window) — per-object RPCs would clog the daemon under churn."""
        for loc in locations:
            self._free_buffer.setdefault(loc, []).append(oid.binary())
        if self._free_flusher is None or self._free_flusher.done():
            self._free_flusher = asyncio.ensure_future(self._flush_frees())

    async def _flush_frees(self):
        # Loop until the buffer is empty at a non-awaiting point: frees
        # that arrive DURING the RPC awaits below must not strand until
        # some later free reschedules the flusher.
        while True:
            await asyncio.sleep(0.05)
            buffered, self._free_buffer = self._free_buffer, {}
            if not buffered:
                return
            nodes = await self._node_table()
            for loc, ids in buffered.items():
                addr = nodes.get(loc)
                if addr:
                    try:
                        await self.pool.get(addr).call(
                            "NodeManager", "FreeObjects", {"ids": ids})
                    except Exception:
                        pass
            if not self._free_buffer:
                return

    # ------------------------------------------------------------------
    # Execution loop (worker mode)
    # ------------------------------------------------------------------

    def run_task_loop(self):
        """Blocks executing tasks until KillActor/shutdown
        (reference: CoreWorker::RunTaskExecutionLoop via default_worker.py).

        Actor tasks are dispatched by the actor's concurrency mode
        (reference: transport/concurrency_group_manager.h):
        - default: run inline on this thread, strictly serialized;
        - max_concurrency>1: run on a thread pool of that size;
        - async actor (any coroutine method): scheduled on a dedicated
          asyncio loop, bounded by a semaphore.
        """
        import contextlib
        stop = False
        while not stop:
            burst = [self.exec_queue.get()]
            while True:
                try:
                    burst.append(self.exec_queue.get_nowait())
                except queue.Empty:
                    break
            # Replies of a burst coalesce into one native flush per conn
            # (a per-reply enqueue costs an io wakeup; see NativeReceiver).
            rx = getattr(self, "_native_rx", None)
            scope = rx.batch_scope() if rx is not None \
                else contextlib.nullcontext()
            with scope:
                for item in burst:
                    if item is None:
                        stop = True
                        break
                    t0 = time.monotonic()
                    self._exec_one_item(item)
                    if rx is not None and time.monotonic() - t0 > 0.002:
                        # Don't hold fast tasks' replies behind a slow
                        # burst neighbour (head-of-line).
                        rx.flush_thread_batch()
        if self._exec_pool is not None:
            self._exec_pool.shutdown(wait=False)
        if self._async_loop is not None:
            self._async_loop.call_soon_threadsafe(self._async_loop.stop)

    def _exec_one_item(self, item):
        spec, done, loop = item
        is_actor_call = spec.actor_id is not None and not spec.actor_creation
        if is_actor_call and self._async_loop is not None:
            def _complete(r, d=done, lp=loop):
                if lp is None:
                    d(r)  # native done-sink: pickles + streams reply
                else:
                    lp.call_soon_threadsafe(
                        lambda: d.done() or d.set_result(r))
            asyncio.run_coroutine_threadsafe(
                self._execute_actor_async(spec, _complete),
                self._async_loop)
        elif is_actor_call and self._exec_pool is not None:
            self._exec_pool.submit(self._run_one, spec, done, loop)
        else:
            self._run_one(spec, done, loop)

    def _run_one(self, spec: TaskSpec, done, loop):
        try:
            reply = self._execute_task(spec)
        except BaseException as e:  # noqa: BLE001 - e.g. a cancel async-exc
            # landing in the sliver between the task body returning and the
            # running-task deregistration; don't kill the exec thread.
            reply = self._error_reply(spec, e)
        if loop is None:
            done(reply)  # native done-sink
        else:
            loop.call_soon_threadsafe(
                lambda d=done, r=reply: d.done() or d.set_result(r))

    def _setup_actor_execution(self, cls, spec: TaskSpec):
        """Choose the actor's execution mode after __init__ succeeds.
        spec.max_concurrency: 0 = unset; async actors then default to the
        reference's 1000, sync actors to 1 (an EXPLICIT 1 on an async actor
        serializes its tasks, as in the reference)."""
        import inspect as _inspect
        is_async = any(
            _inspect.iscoroutinefunction(getattr(cls, name, None))
            for name in dir(cls)
            if not name.startswith("__") or name == "__call__")
        mc = spec.max_concurrency
        if is_async:
            limit = mc if mc > 0 else 1000
            loop = asyncio.new_event_loop()
            self._async_loop = loop
            rx = getattr(self, "_native_rx", None)
            if rx is not None:
                rx.enable_tick_batching(loop)
            self._async_sem = asyncio.Semaphore(limit)
            threading.Thread(target=loop.run_forever, daemon=True,
                             name="actor-async-exec").start()
        elif mc > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._exec_pool = ThreadPoolExecutor(
                max_workers=mc, thread_name_prefix="actor-exec")

    def _record_task_event(self, spec: TaskSpec, started: float,
                           span=None):
        """Buffer one execution event; a loop-side flusher ships batches.
        With tracing on, the event doubles as the task's SPAN: trace_id/
        span_id/parent_id group a driver's whole call tree in the
        timeline (reference: tracing_helper.py spans per task).  The hot
        path appends a tuple; dict shaping happens in the 1 Hz flusher."""
        self._task_events.append(
            (spec.task_id, spec.name, spec.actor_id, started, time.time(),
             span))
        if self._task_event_flusher is None:
            def _start_flusher():
                if self._task_event_flusher is None:
                    self._task_event_flusher = asyncio.ensure_future(
                        self._flush_task_events())
            self.io.loop.call_soon_threadsafe(_start_flusher)

    async def _flush_task_events(self):
        static = {
            "worker_id": self.worker_id.hex()[:12],
            "pid": os.getpid(),
            "node_id": self.node_id.hex()[:12] if self.node_id else "",
        }
        while not self._shutdown:
            await asyncio.sleep(1.0)
            if not self._task_events:
                continue
            batch, self._task_events = self._task_events, []
            events = []
            for task_id, name, actor_id, started, end, span in batch:
                ev = {
                    "task_id": task_id.hex(),
                    "name": name,
                    "actor_id": actor_id.hex() if actor_id else None,
                    "start": started,
                    "end": end,
                    **static,
                }
                if span is not None:
                    ev["trace_id"], ev["span_id"], ev["parent_id"] = span
                events.append(ev)
            try:
                await self.gcs.call("Gcs", "add_task_events",
                                    {"events": events})
            except Exception:
                pass

    def _pack_reply(self, spec: TaskSpec, result) -> dict:
        return {"returns": self._pack_returns(spec, result), "error": None}

    def _error_reply(self, spec: TaskSpec, e: BaseException) -> dict:
        from ray_tpu.exceptions import TaskCancelledError, TrainPreemptedError
        tb = traceback.format_exc()
        logger.info("task %s failed:\n%s", spec.name, tb)
        # TrainPreemptedError stays typed across the wire: the driver
        # routes it to the preemption recovery path (resume from the
        # grace-window save), not the crash path.
        err = e if isinstance(e, (TaskError, ActorDiedError,
                                  TaskCancelledError, TrainPreemptedError)) \
            else TaskError(spec.name, tb, None)
        return {"returns": [], "error": err}

    async def _execute_actor_async(self, spec: TaskSpec, complete):
        """Async-actor execution path: every method runs on the actor's
        event loop (reference semantics — a blocking sync method blocks the
        loop; use a threaded actor for blocking work).  Arg resolution may
        touch the network, so it runs in an executor, concurrently.
        `complete(reply_dict)` delivers the result (transport-agnostic)."""
        import inspect as _inspect
        async with self._async_sem:
            try:
                loop = asyncio.get_running_loop()

                async def resolve(a):
                    # Inline ValueArgs deserialize in-memory — no executor
                    # hop; only ObjectRef args (which may hit the network)
                    # go to the thread pool.
                    if isinstance(a, ValueArg):
                        return self._resolve_arg(a)
                    return await loop.run_in_executor(
                        None, self._resolve_arg, a)

                arg_vals, kw_vals = await asyncio.gather(
                    asyncio.gather(*[resolve(a) for a in spec.args]),
                    asyncio.gather(*[resolve(v)
                                     for v in spec.kwargs.values()]))
                kwargs = dict(zip(spec.kwargs.keys(), kw_vals))
                if self.actor_instance is None:
                    raise ActorDiedError(spec.actor_id, "no instance")
                self.current_task_id = spec.task_id
                self.current_task_spec = spec
                # Install the carried trace context: this coroutine runs
                # as its own asyncio task (own contextvar copy), so the
                # set is isolated per concurrent method call.
                span = tracing.enter_task(spec)
                tok_task = (spans.begin("sched", "task",
                                        ctx=(span[0], span[2]),
                                        sid=span[1], name=spec.name)
                            if span is not None else None)
                try:
                    method = getattr(self.actor_instance, spec.method_name)
                    result = method(*arg_vals, **kwargs)
                    if _inspect.iscoroutine(result):
                        result = await result
                finally:
                    spans.end(tok_task)
                    if span is not None:
                        tracing.exit_task()
                reply = self._pack_reply(spec, result)
            except BaseException as e:  # noqa: BLE001
                reply = self._error_reply(spec, e)
            finally:
                self.current_task_spec = None
            complete(reply)

    def _execute_task(self, spec: TaskSpec) -> dict:
        from ray_tpu.exceptions import TaskCancelledError
        from ray_tpu._private.fault_injection import get_chaos
        chaos = get_chaos()
        if chaos is not None and self.mode == "worker" \
                and chaos.kill_worker():
            # Injected preemption: die BEFORE touching the task, exactly
            # like a SIGKILL'd/preempted worker — the owner sees the
            # connection drop and must retry/reconstruct.
            logger.warning("chaos: killing worker before task %s", spec.name)
            from ray_tpu.util import events
            events.record("proc", "chaos_kill", task=spec.name,
                          trace=getattr(spec, "trace_ctx", None))
            events.dump_crash("chaos_kill_worker")
            os._exit(1)
        _t0 = time.time()
        if spec.task_id in self._cancelled_exec:
            self._cancelled_exec.discard(spec.task_id)
            return {"returns": [],
                    "error": TaskCancelledError(f"task {spec.name} cancelled")}
        with self._cancel_lock:
            self._running_tasks[spec.task_id] = threading.get_ident()
        span = tracing.enter_task(spec)  # nested submits join the trace
        # The task's own span reuses enter_task's span id, so the phase
        # spans below (and any nested submits) hang off it as children.
        tok_task = (spans.begin("sched", "task", ctx=(span[0], span[2]),
                                sid=span[1], name=spec.name)
                    if span is not None else None)
        try:
            tok = spans.begin("sched", "arg_fetch",
                              n=len(spec.args) + len(spec.kwargs)) \
                if tok_task is not None else None
            args = [self._resolve_arg(a) for a in spec.args]
            kwargs = {k: self._resolve_arg(v) for k, v in spec.kwargs.items()}
            spans.end(tok)
            self.current_task_id = spec.task_id
            self.current_task_spec = spec
            if spec.actor_creation:
                cls = self.fn_manager.fetch_cached(spec.fn_key) or \
                    self.io.run(self.fn_manager.fetch(spec.fn_key))
                self.current_actor_pg = spec.placement_group
                self.actor_instance = cls(*args, **kwargs)
                self._setup_actor_execution(cls, spec)
                return {"returns": [], "error": None}
            tok = spans.begin("sched", "exec", name=spec.name) \
                if tok_task is not None else None
            if spec.actor_id is not None:
                if self.actor_instance is None:
                    raise ActorDiedError(spec.actor_id, "no instance")
                method = getattr(self.actor_instance, spec.method_name)
                result = method(*args, **kwargs)
                if asyncio.iscoroutine(result):
                    # Sync-mode actor with an occasional async method.
                    result = asyncio.run(result)
            else:
                fn = self.fn_manager.fetch_cached(spec.fn_key) or \
                    self.io.run(self.fn_manager.fetch(spec.fn_key))
                result = fn(*args, **kwargs)
            spans.end(tok)
            tok = spans.begin("sched", "result_seal") \
                if tok_task is not None else None
            reply = self._pack_reply(spec, result)
            spans.end(tok)
            return reply
        except BaseException as e:  # noqa: BLE001
            return self._error_reply(spec, e)
        finally:
            spans.end(tok_task)
            if span is not None:
                tracing.exit_task()
            with self._cancel_lock:
                self._running_tasks.pop(spec.task_id, None)
            self._cancelled_exec.discard(spec.task_id)
            self._record_task_event(spec, _t0, span)
            # Don't leak this task's context (e.g. its placement group) to
            # whatever runs on this reused worker next.
            self.current_task_spec = None

    def _resolve_arg(self, arg):
        if isinstance(arg, ValueArg):
            return ser.deserialize(arg.data, arg.metadata)
        ref = ObjectRef(ObjectID(arg.id_binary), arg.owner_address,
                        _register=False)
        return self.get(ref)

    def _pack_returns(self, spec: TaskSpec, result) -> list:
        if spec.num_returns == 1:
            results = [result]
        else:
            results = list(result)
            if len(results) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} declared num_returns="
                    f"{spec.num_returns} but returned {len(results)} values")
        packed = []
        for i, value in enumerate(results):
            oid = ObjectID.for_return(spec.task_id, i)
            sv = ser.serialize(value, ref_sink=self._pin_serialized_ref)
            if sv.total_size < INLINE_LIMIT or self.store is None:
                packed.append(("inline", sv.to_bytes(), sv.metadata))
            else:
                if not self.store.contains(oid):
                    try:
                        view = self.store.create_object(
                            oid, sv.total_size, sv.metadata)
                        sv.write_into(view)
                        self.store.seal(oid)
                    except Exception:
                        packed.append(("inline", sv.to_bytes(), sv.metadata))
                        continue
                packed.append(("location", self.node_id.hex(), sv.metadata))
        return packed

    # ------------------------------------------------------------------

    def shutdown(self):
        self._shutdown = True
        object_ref_mod._install_hooks(None)
        try:
            self.io.run(self._drain_leases(), timeout=5)
        except Exception:
            pass
        if self.mode == "driver":
            # Job-scoped cleanup: non-detached placement groups (and their
            # reserved bundles) die with the driver (reference: GCS job
            # manager cleanup on driver exit).
            try:
                self.io.run(self.gcs.call(
                    "Gcs", "cleanup_job", {"job_id": self._job_int()},
                    timeout=10))
            except Exception:
                pass
        for native in (self._native_sub, self._native_rx):
            if native:
                try:
                    native.close()
                except Exception:
                    pass
        try:
            self.io.run(self.server.stop())
            self.io.run(self.pool.close_all())
            self.io.run(self.gcs.close())
        except Exception:
            pass
        self.io.stop()
        if self.store is not None:
            self.store.close()

    # hooks used by ObjectRef.future()/await
    def as_future(self, ref: ObjectRef):
        import concurrent.futures
        fut = concurrent.futures.Future()

        async def run():
            try:
                fut.set_result(await self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        self.io.spawn(run())
        return fut

    async def await_ref(self, ref: ObjectRef):
        return await self._get_one(ref, None)


class _KeyScheduler:
    """Per-SchedulingKey task queue + lease pool.

    Reference: CoreWorkerDirectTaskSubmitter (direct_task_transport.h:75) —
    tasks queue client-side by key; worker leases are requested at a capped
    rate while the queue is non-empty; each granted lease executes queued
    tasks back-to-back (OnWorkerIdle) with ONE PushTask RPC per task; idle
    leases are returned after a TTL.
    """

    def __init__(self, worker: "CoreWorker", key: tuple, proto_spec,
                 exclude: list):
        # Flags snapshot (reference: max_pending_lease_requests / lease TTL
        # — RAY_TPU_* flags in _private/config.py).  Read once: these sit
        # in the per-task dispatch loop.
        from ray_tpu._private.config import GLOBAL_CONFIG
        self.MAX_PENDING_LEASES = GLOBAL_CONFIG.max_pending_lease_requests
        self.IDLE_TTL = GLOBAL_CONFIG.lease_idle_ttl_s
        self.DEPTH = GLOBAL_CONFIG.lease_pipeline_depth
        self.BATCH_MAX = max(1, GLOBAL_CONFIG.sched_batch_max)
        self.worker = worker
        self.key = key
        self.proto_spec = proto_spec     # any spec with this key (for pick)
        self.exclude = exclude
        self.queue: deque = deque()      # (spec, fut, exclusive)
        self.leases: list = []           # granted leases (dicts)
        self.pending_leases = 0          # requested-but-ungranted workers
        self._reaper = None
        # Guards lease membership + inflight counts: the submitting
        # thread may claim a slot directly (try_direct) while the loop
        # dispatches/reaps.  Loop-side sections are short and
        # uncontended in the common case.
        self.tlock = threading.Lock()

    @property
    def held(self):
        return len(self.leases)

    # -- public -----------------------------------------------------------
    async def submit(self, spec, exclusive: bool = False) -> dict:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.queue.append((spec, fut, exclusive))
        self._pump()
        return await fut

    def submit_nowait(self, spec, batches=None):
        """Fast-path enqueue: completion flows straight into the owner's
        object table (sink None) — no future, no coroutine.  An external
        `batches` dict lets a burst of submissions share one native
        flush per worker (_drain_fast owns the flush then)."""
        self.queue.append((spec, None, False))
        self._pump(batches)

    def try_direct(self, pending, spec) -> bool:
        """Caller-thread dispatch for a dependency-free native task:
        claim a free lease slot under tlock and write the frame from
        THIS thread (the C layer writevs inline on an idle connection)
        — the submit never touches the event loop.  Safe because a task
        with no ref args can never wait on anything, so putting it
        ahead of still-queued submissions cannot create a waits-on
        cycle (see _pump's dependency-safety sketch)."""
        worker = self.worker
        sub = worker._native_sub
        if not sub:
            return False
        with self.tlock:
            if self.queue:
                return False     # loop-side work queued: keep FIFO
            best = None
            for lease in self.leases:
                if lease["inflight"] < self.DEPTH and (
                        best is None
                        or lease["inflight"] < best["inflight"]):
                    best = lease
            if best is None:
                return False
            naddr = worker._native_addrs.get(best["worker_address"])
            if not naddr:
                return False
            best["inflight"] += 1
        pending.worker_address = best["worker_address"]
        tok = (spans.begin("sched", "dispatch", ctx=spec.trace_ctx,
                           name=spec.name, zero_hop=True)
               if getattr(spec, "trace_ctx", None) is not None else None)
        if tok is None:
            cb = (lambda status, data: self._on_push_done(
                spec, None, best, status, data))
        else:
            itok = spans.begin("sched", "inflight", ctx=spec.trace_ctx,
                               name=spec.name)

            def cb(status, data, _itok=itok):
                spans.end(_itok, status=status)
                self._on_push_done(spec, None, best, status, data)
        sub.call_spec_batch(naddr, [(pending.payload, pending.template,
                                     cb)])
        spans.end(tok)
        return True

    async def drain(self):
        if self._reaper is not None:
            self._reaper.cancel()
            await asyncio.gather(self._reaper, return_exceptions=True)
            self._reaper = None
        with self.tlock:
            leases, self.leases = self.leases, []
        for lease in leases:
            await self.worker._return_lease(lease)

    def purge_node(self, node_hex: str) -> int:
        """Forget every lease on a dead node WITHOUT a return RPC (the
        daemon is gone) and re-pump so queued work leases elsewhere.
        In-flight pushes on the purged leases fail through their own
        transport callbacks, which find the lease already removed and
        route each task into the normal retry machinery."""
        def _hex(nid):
            h = getattr(nid, "hex", None)
            return h() if callable(h) else nid
        with self.tlock:
            dead = [l for l in self.leases
                    if _hex(l.get("node_id")) == node_hex]
            for lease in dead:
                self.leases.remove(lease)
        for lease in dead:
            self.worker.pool.invalidate(lease["worker_address"])
            self.worker._native_addrs.pop(lease["worker_address"], None)
        if dead:
            self._pump()
        return len(dead)

    # -- internals ---------------------------------------------------------
    def _pump(self, batches=None):
        """Dispatch queued tasks onto held leases, several in flight per
        lease (reference OnWorkerIdle:151 pushes every queued task onto a
        granted lease; the receiver queues them).  Retried tasks dispatch
        exclusively (sole occupant of a lease): normal submissions enter
        worker FIFOs in program order, so a task can only ever wait behind
        strictly-earlier tasks — a retry would break that invariant and
        could park a dependency behind its dependent.

        Dependency-safety sketch: waits-on edges (arg refs) always point to
        earlier-submitted tasks; per-worker FIFOs are subsequences of
        submission order (exclusive retries exempt but never queued behind
        anything); hence the waits-on relation is acyclic and the earliest
        blocked task's dependency is always running or done."""
        flush_here = batches is None
        if batches is None:
            batches = {}   # native addr -> list[(payload, cb)]
        while self.queue:
            spec, sink, exclusive = self.queue[0]
            cap = 1 if exclusive else self.DEPTH
            with self.tlock:
                best = None
                for lease in self.leases:
                    if lease["inflight"] < cap and (
                            best is None
                            or lease["inflight"] < best["inflight"]):
                        best = lease
                if best is None or (exclusive and best["inflight"] > 0):
                    break
                best["inflight"] += 1
            self.queue.popleft()
            self._dispatch(spec, sink, best, batches)
        if flush_here and batches:
            for naddr, items in batches.items():
                self.worker._ship_batch(naddr, items)
        # Lease demand scales by pipeline depth (a lease carries DEPTH
        # tasks).  Anything still queued found every held lease full, so
        # the remaining queue needs NEW leases; only the number of
        # in-flight lease GRANTS is capped (reference
        # lease_policy/max_pending_lease_requests_per_scheduling_category)
        # — total held leases are bounded by cluster resources at the
        # hostd, not by the client.  Demand is amortized into batched
        # requests: ONE LeaseWorker RPC carries up to BATCH_MAX grants,
        # so a deep queue costs ceil(want / BATCH_MAX) round trips
        # instead of `want`.
        want = min((len(self.queue) + self.DEPTH - 1) // self.DEPTH
                   - self.pending_leases,
                   self.MAX_PENDING_LEASES - self.pending_leases)
        while want > 0:
            n = min(want, self.BATCH_MAX)
            want -= n
            self.pending_leases += n
            asyncio.ensure_future(self._acquire_lease(n))

    def _dispatch(self, spec, sink, lease, batches):
        """Native-route dispatches accumulate into `batches` (flushed by
        the _pump that owns the dict — one library call per worker);
        unknown routes (fresh worker, native off) take the coroutine
        path, which performs discovery."""
        worker = self.worker
        pending = worker.tasks.get(spec.task_id)
        if pending is not None:
            pending.worker_address = lease["worker_address"]
            if pending.q_span is not None:
                spans.end(pending.q_span)
                pending.q_span = None
        tok = (spans.begin("sched", "dispatch", ctx=spec.trace_ctx,
                           name=spec.name)
               if getattr(spec, "trace_ctx", None) is not None else None)
        if (pending is not None and pending.payload is not None
                and worker._native_sub):
            naddr = worker._native_addrs.get(lease["worker_address"])
            if naddr:
                if tok is None:
                    cb = (lambda status, data: self._on_push_done(
                        spec, sink, lease, status, data))
                else:
                    # Residency on the worker's pipeline (shipped ->
                    # push completion) is its own span; the dispatch
                    # token is closed by _ship_batch once the frame is
                    # handed to the transport.
                    itok = spans.begin("sched", "inflight",
                                       ctx=spec.trace_ctx, name=spec.name)

                    def cb(status, data, _itok=itok):
                        spans.end(_itok, status=status)
                        self._on_push_done(spec, sink, lease, status, data)
                batches.setdefault(naddr, []).append(
                    (pending.payload, pending.template, cb, tok))
                return
        asyncio.ensure_future(self._run_on_lease(spec, sink, lease, tok))

    def _on_push_done(self, spec, sink, lease, status, data):
        """Completion callback for zero-coroutine native pushes (runs
        inline on the io loop, one batch of these per loop wakeup)."""
        worker = self.worker
        if status != 0:
            worker.pool.invalidate(lease["worker_address"])
            with self.tlock:
                dead = lease in self.leases
                if dead:
                    self.leases.remove(lease)
            if dead:
                asyncio.ensure_future(
                    worker._return_lease(lease, kill=True))
            self._deliver(spec, sink, None, _RetryableSubmitError(
                "worker died: native connection closed",
                lease.get("node_id")))
            self._pump()
            return
        with self.tlock:
            lease["inflight"] -= 1
            if lease["inflight"] == 0:
                lease["idle_since"] = time.monotonic()
        try:
            reply = spec_codec.reply_from_wire(data)
        except BaseException as e:  # noqa: BLE001
            self._deliver(spec, sink, None, e)
            self._pump()
            return
        self._deliver(spec, sink, reply, None)
        if self._reaper is None:
            self._reaper = asyncio.ensure_future(self._reap_idle())
        # Completion batches deliver many of these callbacks per loop
        # tick; their re-dispatches coalesce into one flush per worker.
        self._pump(self.worker._shared_batches())

    def _deliver(self, spec, sink, reply, exc):
        """Resolve one dispatched task: slow path -> its future; fast path
        (sink None) -> finalize the owner's object table directly, with
        failures handed to the coroutine retry machinery."""
        worker = self.worker
        if sink is not None:
            if sink.done():
                return
            if exc is not None:
                sink.set_exception(exc)
            else:
                sink.set_result(reply)
            return
        if exc is not None:
            asyncio.ensure_future(
                worker._resume_task_fast(spec.task_id, exc))
            return
        err = reply.get("error")
        pending = worker.tasks.get(spec.task_id)
        if err is not None and spec.retry_exceptions \
                and pending is not None and pending.retries_left > 0 \
                and not pending.cancelled:
            from ray_tpu.exceptions import TaskCancelledError
            if not isinstance(err, TaskCancelledError):
                asyncio.ensure_future(
                    worker._resume_task_fast(spec.task_id, None))
                return
        worker._complete_task_reply(spec, reply)

    def _fail_one(self, exc: BaseException):
        """Deliver a lease failure to one queued task (its retry loop in
        _run_task_to_completion decides what happens next)."""
        while self.queue:
            spec, sink, _excl = self.queue.popleft()
            if sink is None or not sink.done():
                self._deliver(spec, sink, None, exc)
                return

    def _maybe_gc(self):
        """Drop this scheduler from the cache when fully idle — otherwise
        keys that never got a lease (failed/excluded nodes) accumulate."""
        if not self.queue and not self.leases \
                and not self.pending_leases:
            if self._reaper is not None:
                self._reaper.cancel()
                self._reaper = None
            self.worker._lease_cache.pop(self.key, None)

    async def _run_on_lease(self, spec, sink, lease, tok=None):
        pending = self.worker.tasks.get(spec.task_id)
        if pending is not None:
            pending.worker_address = lease["worker_address"]
        try:
            reply = await self.worker._push_on_lease(spec, lease)
            spans.end(tok, status=0)
        except Exception as e:
            spans.end(tok, status=1)
            self.worker.pool.invalidate(lease["worker_address"])
            with self.tlock:
                dead = lease in self.leases
                if dead:
                    self.leases.remove(lease)
            if dead:
                await self.worker._return_lease(lease, kill=True)
            self._deliver(spec, sink, None, _RetryableSubmitError(
                f"worker died: {e}", lease.get("node_id")))
            self._pump()
            return
        with self.tlock:
            lease["inflight"] -= 1
            if lease["inflight"] == 0:
                lease["idle_since"] = time.monotonic()
        self._deliver(spec, sink, reply, None)
        if self._reaper is None:
            self._reaper = asyncio.ensure_future(self._reap_idle())
        # Completion batches deliver many of these callbacks per loop
        # tick; their re-dispatches coalesce into one flush per worker.
        self._pump(self.worker._shared_batches())

    async def _acquire_lease(self, count: int = 1):
        """Request up to `count` worker grants in ONE LeaseWorker RPC.
        The hostd grants what it can immediately (parking only when it
        can grant zero); a partial fill resolves here and the follow-up
        _pump re-requests the remainder."""
        worker = self.worker
        spec = self.proto_spec
        # Lease demand is driven by the queue head: attribute the wait to
        # the trace actually blocked on it (specs sharing a key share the
        # lease, so this is the lease's best single owner).
        head = self.queue[0][0] if self.queue else spec
        tok = (spans.begin("sched", "lease_wait",
                           ctx=getattr(head, "trace_ctx", None),
                           key=str(self.key)[:64], count=count)
               if getattr(head, "trace_ctx", None) is not None else None)
        try:
            bundle = None
            if spec.placement_group is not None:
                node, bundle = await worker._resolve_bundle(spec)
            else:
                # Locality hint: count owned object args per holding node
                # (reference: lease_policy.h LocalityAwareLeasePolicy asks
                # the locality-data provider for object-bytes-per-node).
                # Read args off the task actually WAITING, not proto_spec —
                # tasks sharing a scheduling key differ in their args, and
                # the first-ever spec's locations must not steer every
                # later lease (reference keys include depended_object_ids).
                loc_spec = self.queue[0][0] if self.queue else spec
                locality: dict[str, int] = {}
                if spec.scheduling_strategy in (None, "DEFAULT"):
                    from ray_tpu._private.protocol import RefArg
                    from ray_tpu._private.ids import ObjectID
                    ref_args = [a for a in list(loc_spec.args)
                                + list(loc_spec.kwargs.values())
                                if isinstance(a, RefArg)]
                    for a in ref_args:
                        st = worker.objects.get(ObjectID(a.id_binary))
                        if st is not None:
                            for loc in st.locations:
                                locality[loc] = locality.get(loc, 0) + 1
                pick = await worker.gcs.call("Gcs", "pick_node", {
                    "resources": spec.resources.to_dict(),
                    "strategy": spec.scheduling_strategy,
                    "exclude": self.exclude,
                    "node_affinity": spec.node_affinity,
                    "locality": locality or None,
                })
                node = pick["node"]
            if node is None:
                if self.exclude:
                    raise _RetryableSubmitError(
                        "all feasible nodes excluded", None, busy=True)
                raise ValueError(
                    f"no node can satisfy resources "
                    f"{spec.resources.to_dict()} for task {spec.name}")
            try:
                lease = await worker.pool.get(node.address).call(
                    "NodeManager", "LeaseWorker",
                    {"resources": spec.resources.to_dict(),
                     "job_id": worker._job_int(), "bundle": bundle,
                     "runtime_env": spec.runtime_env,
                     "count": count},
                    timeout=60)
            except Exception as e:
                raise _RetryableSubmitError(f"lease rpc failed: {e}",
                                            node.node_id)
            if not lease.get("granted"):
                raise _RetryableSubmitError(
                    f"lease rejected: {lease.get('reason')}", node.node_id,
                    busy=lease.get("reason") in ("busy", "resources"))
        except BaseException as e:  # noqa: BLE001 - routed to a queued task
            spans.end(tok, granted=False)
            self.pending_leases -= count
            # A busy rejection while we HOLD leases is not a task failure:
            # queued tasks are draining through the held workers; failing
            # one would send it to the back of the queue after a pointless
            # 0.1s sleep.  Only surface busy when no progress is possible.
            busy = isinstance(e, _RetryableSubmitError) and e.busy
            if busy and (self.held > 0 or self.pending_leases > 0):
                return
            if not isinstance(e, _RetryableSubmitError):
                # Permanent infeasibility applies to EVERY queued task with
                # this key — failing just one would strand the rest.
                while self.queue:
                    self._fail_one(e)
                self._maybe_gc()
                return
            self._fail_one(e)
            # Re-pump: remaining queued tasks still need leases, and the
            # task we just failed may never resubmit (cancelled, retries
            # exhausted) — without this they'd strand with no lease
            # requests in flight.
            self._pump()
            self._maybe_gc()
            return
        # A batched reply carries one grant dict per worker; a legacy
        # single-grant reply IS the grant.  Partial fills are normal —
        # the hostd returns what it could satisfy without parking.
        grants = lease.get("grants") or [lease]
        spans.end(tok, granted=True, grants=len(grants))
        self.pending_leases -= count
        fresh = []
        for g in grants:
            g = dict(g)
            g["node_address"] = node.address
            g["node_id"] = node.node_id
            g["idle_since"] = time.monotonic()
            g["inflight"] = 0
            port = g.get("native_port", 0)
            waddr = g.get("worker_address", "")
            if port and waddr and waddr not in worker._native_addrs:
                # The grant carries the worker's native route: the FIRST
                # push to a fresh worker already goes over the native
                # plane (no NativePort discovery RPC, no coroutine
                # detour).
                worker._native_addrs[waddr] = (
                    f"{waddr.rsplit(':', 1)[0]}:{port}")
            fresh.append(g)
        with self.tlock:
            self.leases.extend(fresh)
        if self._reaper is None:
            self._reaper = asyncio.ensure_future(self._reap_idle())
        self._pump()

    async def _reap_idle(self):
        try:
            while True:
                await asyncio.sleep(self.IDLE_TTL / 2)
                now = time.monotonic()
                with self.tlock:
                    # Remove under the lock BEFORE returning: a direct
                    # dispatcher must never claim a lease being reaped.
                    expire = [l for l in self.leases
                              if l["inflight"] == 0
                              and now - l["idle_since"] > self.IDLE_TTL]
                    for lease in expire:
                        self.leases.remove(lease)
                for lease in expire:
                    await self.worker._return_lease(lease)
                if not self.leases and not self.queue \
                        and not self.pending_leases:
                    self.worker._lease_cache.pop(self.key, None)
                    self._reaper = None
                    return
        except asyncio.CancelledError:
            pass


class _RefHooks:
    """Bridges ObjectRef lifecycle events to the core worker."""

    def __init__(self, cw: CoreWorker):
        self.cw = cw

    def on_ref_created(self, ref):
        self.cw.on_ref_created(ref)

    def on_ref_deleted(self, ref):
        self.cw.on_ref_deleted(ref)

    def on_ref_serialized(self, ref):
        pass  # pinning handled via serializer ref_sink

    def on_ref_deserialized(self, ref):
        self.cw.on_ref_deserialized(ref)

    def as_future(self, ref):
        return self.cw.as_future(ref)

    def await_ref(self, ref):
        return self.cw.await_ref(ref)


class _RetryableSubmitError(Exception):
    def __init__(self, msg: str, node_id, busy: bool = False):
        super().__init__(msg)
        self.node_id = node_id
        self.busy = busy  # transient saturation: requeue without burning retries
