"""Scheduling policies over the cluster resource view.

Reference parity: src/ray/raylet/scheduling/policy/ —
hybrid_scheduling_policy.cc (default: pack until a utilization threshold,
then best node), spread_scheduling_policy.cc, node_affinity_scheduling_policy.cc.
The admission decision stays with each node's daemon (leases can still be
rejected and rescheduled), so this view only has to be approximately fresh —
same split as raylet spillback.
"""

from __future__ import annotations

from ray_tpu._private.protocol import NodeInfo

def _threshold() -> float:
    from ray_tpu._private.config import GLOBAL_CONFIG
    return GLOBAL_CONFIG.scheduler_spread_threshold


HYBRID_THRESHOLD = 0.5  # reference default; live value via _threshold()


def _fits(available: dict, demand: dict) -> bool:
    for k, v in demand.items():
        if v > 0 and available.get(k, 0.0) + 1e-9 < v:
            return False
    return True


def _utilization(node: NodeInfo) -> float:
    worst = 0.0
    for k, total in node.resources_total.items():
        if total > 0:
            used = total - node.resources_available.get(k, 0.0)
            worst = max(worst, used / total)
    return worst


def pick_node(nodes: list[NodeInfo], demand: dict, strategy: str = "DEFAULT",
              exclude: set | None = None, affinity=None,
              affinity_soft: bool = True,
              locality: dict | None = None) -> NodeInfo | None:
    """Returns the target node, or None only if NO node's total capacity can
    ever satisfy the demand (infeasible).  When everything is momentarily
    busy, a feasible node is still returned — the lease queues at its daemon,
    matching the reference's raylet dispatch queues."""
    exclude = exclude or set()
    candidates = [n for n in nodes if n.node_id not in exclude
                  and _fits(n.resources_available, demand)]
    fits_now = bool(candidates)
    if not candidates:
        candidates = [n for n in nodes if n.node_id not in exclude
                      and _fits(n.resources_total, demand)]
    if affinity is not None:
        for n in candidates:
            if n.node_id == affinity:
                return n
        if not affinity_soft:
            return None
    if not candidates:
        return None
    if strategy == "SPREAD":
        # Least utilized first (spread_scheduling_policy.cc round-robins over
        # feasible nodes; least-utilized achieves the same steady state).
        return min(candidates, key=_utilization)
    if strategy == "RANDOM":
        # reference: random_scheduling_policy.cc — uniform over feasible.
        import random
        return random.choice(candidates)
    if locality and fits_now:
        # Locality-aware lease target: run where the task's object args
        # already live (reference: lease_policy.h LocalityAwareLeasePolicy
        # — best node by object bytes local).  Only among nodes with free
        # capacity RIGHT NOW (a saturated holder would queue the lease;
        # the reference equivalent is raylet spillback), else hybrid.
        best = max(candidates,
                   key=lambda n: locality.get(n.node_id.hex(), 0))
        if locality.get(best.node_id.hex(), 0) > 0:
            return best
    # Hybrid/DEFAULT: pack onto already-busy nodes while below the threshold
    # so small tasks don't fragment the fleet, else fall back to best
    # (least-utilized) node.
    below = [n for n in candidates if _utilization(n) < _threshold()]
    if below:
        return max(below, key=_utilization)
    return min(candidates, key=_utilization)
