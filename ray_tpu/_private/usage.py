"""Usage statistics (reference: python/ray/_private/usage/ — opt-out
cluster usage reporting).  This deployment is network-isolated, so
reports are only ever written LOCALLY (session dir usage_stats.json);
nothing leaves the machine.  Disabled entirely with
RAY_TPU_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def collect_usage(extra: Dict[str, Any] | None = None) -> Dict[str, Any]:
    import platform
    import ray_tpu
    stats = {
        "ray_tpu_version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
        "recorded_at": time.time(),
    }
    import sys
    jax = sys.modules.get("jax")  # never cold-import jax on the init path
    if jax is not None:
        stats["jax_version"] = getattr(jax, "__version__", "?")
    stats.update(extra or {})
    return stats


def record_usage(session_dir: str,
                 extra: Dict[str, Any] | None = None) -> str | None:
    """Write the local usage report; returns the path (or None when
    disabled)."""
    if not usage_stats_enabled():
        return None
    path = os.path.join(session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(collect_usage(extra), f, indent=2)
        return path
    except OSError:
        return None
