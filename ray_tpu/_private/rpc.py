"""RPC layer: gRPC with string-routed methods, no generated stubs.

Equivalent of the reference's src/ray/rpc/ (client_call.h, grpc_server.h):
every daemon exposes gRPC services, every client keeps a channel pool.
We route by method path (/raytpu.<Service>/<Method>) with pickled payloads —
the service layer is plain async Python functions.  The transport is real
gRPC (HTTP/2 multiplexing, flow control), so a future C++ service can drop in
behind the same method names.

Control-plane payloads are small dicts; the object-transfer path passes
`bytes` through untouched (no pickle copy) via a raw marker.
"""

from __future__ import annotations

import asyncio
import pickle
import random
import threading
import time
from typing import Any, Callable

import grpc
import grpc.aio

from .config import GLOBAL_CONFIG
from .fault_injection import ChaosInjectedError, get_chaos

_MAX_MSG = 512 * 1024 * 1024
_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
    ("grpc.so_reuseport", 0),
]

_RAW = b"\x01"  # payload is raw bytes
_PKL = b"\x00"  # payload is pickled
_PB = b"\x03"   # payload is a typed proto message (ray_tpu.protocol)


def _dumps(obj: Any) -> bytes:
    if type(obj) is bytes:
        return _RAW + obj
    if hasattr(obj, "DESCRIPTOR") and hasattr(obj, "SerializeToString"):
        from ray_tpu import protocol
        return _PB + protocol.encode(obj)
    return _PKL + pickle.dumps(obj, protocol=5)


def _loads(data: bytes) -> Any:
    tag = data[:1]
    if tag == _RAW:
        return data[1:]
    if tag == _PB:
        from ray_tpu import protocol
        return protocol.decode(data[1:])
    return pickle.loads(data[1:])


class RpcError(Exception):
    """A remote handler raised; carries the remote exception."""

    def __init__(self, method: str, remote_exc: BaseException):
        self.method = method
        self.remote_exc = remote_exc
        super().__init__(f"{method} failed remotely: {remote_exc!r}")


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, methods: dict[str, Callable]):
        self._methods = methods

    def service(self, handler_call_details):
        fn = self._methods.get(handler_call_details.method)
        if fn is None:
            return None

        async def unary(request: bytes, context) -> bytes:
            try:
                result = await fn(_loads(request))
                return _dumps(result)
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 - ship to caller
                return b"\x02" + pickle.dumps(e, protocol=5)

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


class RpcServer:
    """Async gRPC server hosting one or more services.

    Handlers are `async def handler(request) -> response` registered under
    ("Service", "Method").
    """

    def __init__(self, host: str = "127.0.0.1"):
        self._host = host
        self._methods: dict[str, Callable] = {}
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    def register(self, service: str, method: str, handler: Callable):
        self._methods[f"/raytpu.{service}/{method}"] = handler

    def register_service(self, service: str, obj: Any):
        """Register every public async method of `obj`."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if asyncio.iscoroutinefunction(fn):
                self.register(service, name, fn)

    async def start(self, port: int = 0) -> int:
        self._server = grpc.aio.server(options=_OPTIONS)
        self._server.add_generic_rpc_handlers((_Handler(self._methods),))
        self.port = self._server.add_insecure_port(f"{self._host}:{port}")
        await self._server.start()
        return self.port

    async def stop(self, grace: float = 0.5):
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


class RpcClient:
    """Channel to one remote server; call methods by (service, method)."""

    def __init__(self, address: str):
        self.address = address
        self._channel = None  # created lazily inside the running event loop
        self._callables: dict[str, Any] = {}

    def _chan(self):
        if self._channel is None:
            self._channel = grpc.aio.insecure_channel(
                self.address, options=_OPTIONS)
        return self._channel

    def _callable(self, path: str):
        # MultiCallable construction is surprisingly expensive in grpc.aio
        # (~ms); cache one per method path (reference: generated stubs hold
        # them for the process lifetime).
        rpc = self._callables.get(path)
        if rpc is None:
            rpc = self._chan().unary_unary(
                path, request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            self._callables[path] = rpc
        return rpc

    def _reset_channel(self):
        """Tear down the channel so the next call redials.

        Pooled clients self-heal through this: a disconnect invalidates
        the cached MultiCallables (they hold the dead channel) and the
        retry loop rebuilds both (reference: client_call.h channel
        reconnection on UNAVAILABLE).
        """
        chan, self._channel = self._channel, None
        self._callables.clear()
        if chan is not None:
            # Close asynchronously; we may be mid-retry on the loop.
            try:
                asyncio.ensure_future(chan.close())
            except RuntimeError:
                pass

    @staticmethod
    def _retryable(e: BaseException) -> bool:
        if isinstance(e, ChaosInjectedError):
            return True
        if isinstance(e, grpc.aio.AioRpcError):
            # Only UNAVAILABLE is safely retryable: the request never
            # reached (or never committed on) the peer.  UNKNOWN may mean
            # the handler ran.
            return e.code() == grpc.StatusCode.UNAVAILABLE
        return False

    async def call(self, service: str, method: str, request: Any = None,
                   timeout: float | None = None) -> Any:
        """Invoke a remote method with transparent transient-failure retry.

        `timeout` is the OVERALL deadline for the call, spanning all
        attempts — a liveness probe with timeout=5 still fails within
        ~5s even while retrying.  Only transport-level failures
        (UNAVAILABLE, injected chaos faults) are retried, with
        exponential backoff + jitter; remote handler exceptions
        (RpcError) and DEADLINE_EXCEEDED surface immediately.
        """
        path = f"/raytpu.{service}/{method}"
        payload = _dumps(request)
        cfg = GLOBAL_CONFIG
        deadline = None if timeout is None else time.monotonic() + timeout
        attempt = 0
        while True:
            chaos = get_chaos()
            if chaos is not None:
                if chaos.link_fault(self.address):
                    # Sustained scripted partition: this link is
                    # blackholed right now.  Surface the same way a real
                    # partition does — transport failure, backoff, retry
                    # — so callers exercise their genuine outage paths.
                    err = ChaosInjectedError(
                        f"chaos: link blackhole {self.address}{path}")
                    if not await self._backoff(attempt, deadline, cfg):
                        raise err
                    attempt += 1
                    continue
                fault = chaos.rpc_fault()
                if fault is not None:
                    kind, delay = fault
                    if kind == "delay":
                        await asyncio.sleep(delay)
                    else:
                        if kind == "disconnect":
                            self._reset_channel()
                        err = ChaosInjectedError(
                            f"chaos: {kind} {self.address}{path}")
                        if not await self._backoff(attempt, deadline, cfg):
                            raise err
                        attempt += 1
                        continue
            per_attempt = None
            if deadline is not None:
                per_attempt = deadline - time.monotonic()
                if per_attempt <= 0:
                    raise TimeoutError(
                        f"{path} to {self.address}: deadline exceeded "
                        f"after {attempt} attempt(s)")
            try:
                data = await self._callable(path)(payload,
                                                  timeout=per_attempt)
            except BaseException as e:  # noqa: BLE001 - classified below
                if not self._retryable(e):
                    raise
                self._reset_channel()
                if not await self._backoff(attempt, deadline, cfg):
                    raise
                attempt += 1
                continue
            if data[:1] == b"\x02":
                raise RpcError(path, pickle.loads(data[1:]))
            return _loads(data)

    async def _backoff(self, attempt: int, deadline: float | None,
                       cfg) -> bool:
        """Sleep the exponential backoff for `attempt`; False when the
        retry budget or the deadline is exhausted (caller re-raises)."""
        if attempt >= cfg.rpc_max_retries:
            return False
        delay = min(cfg.rpc_retry_base_ms * (2 ** attempt),
                    cfg.rpc_retry_max_ms) / 1000.0
        delay *= 0.5 + random.random()  # +/-50% jitter, decorrelates peers
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            delay = min(delay, remaining)
        await asyncio.sleep(delay)
        return True

    async def close(self):
        if self._channel is not None:
            await self._channel.close()


_gcs_ft_metrics_cache = None


def _gcs_ft_metrics():
    global _gcs_ft_metrics_cache
    if _gcs_ft_metrics_cache is None:
        from ray_tpu.util import metrics as mt
        _gcs_ft_metrics_cache = {
            "gcs_unreachable_seconds": mt.Counter(
                "gcs_unreachable_seconds",
                "cumulative seconds this process could not reach the GCS"),
            "gcs_outages": mt.Counter(
                "gcs_outages",
                "distinct GCS outage windows observed by this process"),
        }
    return _gcs_ft_metrics_cache


class GcsClient(RpcClient):
    """RpcClient to the GCS head with outage ride-through.

    The GCS is restartable (supervised respawn at the same address from
    its sqlite tables), so a transport failure against it usually means
    "down for seconds", not "gone".  Control-plane calls therefore
    buffer-and-retry across the base client's retry budget, redialing
    until ``gcs_outage_deadline_s``, instead of surfacing every blip to
    scheduling/actor paths.  Only transport-level failures ride through;
    remote handler errors (RpcError) surface immediately.  The data
    plane is peer-to-peer and never routes through this class, so tasks,
    serve streams and train steps keep flowing during the outage.

    Callers that *measure* GCS liveness (the hostd heartbeat loop, whose
    silence window is the node-death input) pass ``outage_retry=False``
    to keep their fail-fast semantics; they still get outage accounting.

    Every outage window is flight-recorded (``gcs/unreachable`` on
    onset, ``gcs/reconnected`` with the duration on recovery) and
    accumulated into the ``gcs_unreachable_seconds`` counter so head
    outages show up in `cli events` / `cli analyze` instead of passing
    silently.
    """

    def __init__(self, address: str):
        super().__init__(address)
        from . import fault_injection
        fault_injection.set_gcs_address(address)
        self._outage_started: float | None = None
        self._outage_acct = 0.0
        self._outage_lock = threading.Lock()

    @staticmethod
    def _transport_failure(e: BaseException) -> bool:
        # TimeoutError here is OUR deadline raise from RpcClient.call —
        # it fires only after retryable transport failures consumed the
        # window, never after a successful attempt.  grpc's own
        # DEADLINE_EXCEEDED (server reached, handler slow) is NOT listed:
        # the request may have committed.
        if isinstance(e, (ConnectionError, TimeoutError)):
            return True
        if isinstance(e, grpc.aio.AioRpcError):
            return e.code() == grpc.StatusCode.UNAVAILABLE
        return False

    def _note_unreachable(self):
        now = time.monotonic()
        first = False
        with self._outage_lock:
            if self._outage_started is None:
                self._outage_started = now
                self._outage_acct = now
                first = True
            else:
                _gcs_ft_metrics()["gcs_unreachable_seconds"].inc(
                    now - self._outage_acct)
                self._outage_acct = now
        if first:
            _gcs_ft_metrics()["gcs_outages"].inc()
            from ray_tpu.util import events
            events.record("gcs", "unreachable", address=self.address)

    def _note_reachable(self):
        with self._outage_lock:
            if self._outage_started is None:
                return
            now = time.monotonic()
            outage = now - self._outage_started
            _gcs_ft_metrics()["gcs_unreachable_seconds"].inc(
                now - self._outage_acct)
            self._outage_started = None
        from ray_tpu.util import events
        events.record("gcs", "reconnected", address=self.address,
                      outage_s=round(outage, 3))

    async def call(self, service: str, method: str, request: Any = None,
                   timeout: float | None = None,
                   outage_retry: bool = True) -> Any:
        deadline = (time.monotonic()
                    + float(GLOBAL_CONFIG.gcs_outage_deadline_s))
        while True:
            try:
                result = await super().call(service, method, request,
                                            timeout=timeout)
            except BaseException as e:  # noqa: BLE001 - classified below
                if not self._transport_failure(e):
                    raise
                self._note_unreachable()
                if not outage_retry or time.monotonic() >= deadline:
                    raise
                # Redial on a short poll: a supervised restart comes back
                # in about a second, and the respawn binds the same
                # address, so a fresh channel is all recovery takes.
                self._reset_channel()
                await asyncio.sleep(
                    min(0.25, max(0.0, deadline - time.monotonic())))
                continue
            self._note_reachable()
            return result


class ClientPool:
    """address -> RpcClient cache (reference: core_worker_client_pool.h)."""

    def __init__(self):
        self._clients: dict[str, RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, address: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(address)
            if c is None:
                c = self._clients[address] = RpcClient(address)
            return c

    def invalidate(self, address: str):
        with self._lock:
            self._clients.pop(address, None)

    async def close_all(self):
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                await c.close()
            except Exception:
                pass


class EventLoopThread:
    """A dedicated asyncio loop on a background thread.

    The synchronous public API (ray_tpu.get/put/remote) drives all async
    networking through this, the way the reference drives C++ asio loops from
    Python via Cython.
    """

    def __init__(self, name: str = "raytpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()
        self.ident = self._thread.ident  # loop-thread id for fast checks

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _drain_and_stop():
            # Cancel housekeeping tasks (lease reapers, flushers) and let
            # the cancellations finish, so the loop drains clean instead
            # of warning 'Task was destroyed but it is pending' at exit.
            # Bounded: a task stuck in an executor call must not keep the
            # loop alive forever.
            me = asyncio.current_task()
            tasks = [t for t in asyncio.all_tasks(self.loop) if t is not me]
            for t in tasks:
                t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(*tasks, return_exceptions=True), 3.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass
            self.loop.stop()

        try:
            self.loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(_drain_and_stop()))
        except RuntimeError:
            pass
        self._thread.join(timeout=5)
        if not self.loop.is_running():
            self.loop.close()
