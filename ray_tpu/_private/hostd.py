"""hostd — the per-node daemon (reference: src/ray/raylet/).

Owns the node's shared-memory object store, the worker pool
(raylet/worker_pool.h: spawn/pop/cache idle workers), local resource
accounting (LocalResourceManager), worker leasing for tasks and actors
(NodeManager::HandleRequestWorkerLease, node_manager.cc:1817), node-to-node
object transfer (object_manager/: pull semantics), and the GCS heartbeat.

Scheduling split, as in the reference: the GCS resource view proposes a node;
this daemon is the admission controller — a lease can be rejected and the
submitter reschedules (spillback).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import deque

from ray_tpu._private import gcs as gcs_mod
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.protocol import NodeInfo
from ray_tpu._private.rpc import ClientPool, GcsClient, RpcClient, RpcServer
from ray_tpu.util import events
from ray_tpu.util import spans

logger = logging.getLogger("ray_tpu.hostd")

def _cfg():
    from ray_tpu._private.config import GLOBAL_CONFIG
    return GLOBAL_CONFIG


def _metrics():
    """Daemon metric definitions (reference: stats/metric_defs.h:46-110)."""
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "leases_granted": mt.Counter(
                "leases_granted", "worker leases granted"),
            "workers_spawned": mt.Counter(
                "workers_spawned", "worker processes spawned"),
            "objects_spilled": mt.Counter(
                "objects_spilled", "objects written to spill storage"),
            "bytes_spilled": mt.Counter(
                "bytes_spilled", "bytes written to spill storage"),
            "objects_restored": mt.Counter(
                "objects_restored", "spilled objects read back"),
            "store_used_bytes": mt.Gauge(
                "store_used_bytes", "shm object store bytes in use"),
            "oom_workers_killed": mt.Counter(
                "oom_workers_killed",
                "workers killed by the memory monitor"),
            "preemption_notices": mt.Counter(
                "preemption_notices",
                "preemption notices received by this hostd"),
            "preemption_grace_s": mt.Gauge(
                "preemption_grace_s",
                "grace window of the most recent preemption notice"),
        }
    return _M


_M = None





def detect_resources() -> dict:
    res = {"CPU": float(os.cpu_count() or 1)}
    # TPU detection: honor explicit env (set by the pod provisioner) first;
    # otherwise probe jax lazily in a subprocess so hostd itself never holds
    # the TPU runtime open.
    if "RAY_TPU_NUM_TPUS" in os.environ:
        n = float(os.environ["RAY_TPU_NUM_TPUS"])
        if n > 0:
            res["TPU"] = n
    # Schedulable memory: 70% of system RAM (reference: resource_spec.py
    # caps the memory resource below total so daemons/OS keep headroom).
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    res["memory"] = float(int(line.split()[1]) * 1024 * 0.7)
                    break
    except OSError:
        pass
    # Accelerator type advertisement (reference: accelerator_type:<T>
    # node resource; util/accelerators knows NVIDIA only — TPU gens here).
    acc = os.environ.get("RAY_TPU_ACCELERATOR_TYPE")
    if acc:
        res[f"accelerator_type:{acc}"] = 1.0
    return res


def _wants_tpu(demand: dict) -> bool:
    """A lease needs TPU runtime access iff it demands a TPU resource
    (``num_tpus`` / ``TPU`` / ``TPU-<gen>-head`` custom resources)."""
    return any(v > 0 and (k == "TPU" or k.startswith("TPU"))
               for k, v in demand.items())


class _ForkedProc:
    """Popen-compatible view of a worker forked by the zygote.

    The child belongs to the zygote's process tree, so exit detection is
    authoritative only through the zygote's reap reports (`exits` — a
    shared {pid: code} map the hostd refreshes each reaper sweep).  The
    kill(pid, 0) probe alone would misreport after pid reuse and always
    lose the exit code; here it only accelerates detection between
    sweeps, and the real code replaces the placeholder when the report
    lands."""

    def __init__(self, pid: int, exits: dict):
        self.pid = pid
        self.returncode: int | None = None
        self._exits = exits

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        code = self._exits.pop(self.pid, None)
        if code is not None:
            self.returncode = code
            return code
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            # Gone but the reap report hasn't arrived yet; report dead
            # with an unknown-exit placeholder (refined above if the
            # report lands before anyone reads it).
            self.returncode = self._exits.pop(self.pid, 255)
            return self.returncode
        except PermissionError:
            return None

    def terminate(self):
        try:
            os.kill(self.pid, 15)
        except ProcessLookupError:
            pass

    def kill(self):
        try:
            os.kill(self.pid, 9)
        except ProcessLookupError:
            pass

    def wait(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(f"pid {self.pid}", timeout)
            time.sleep(0.02)
        return self.returncode


class _Zygote:
    """Manages the fork-server process (see worker_zygote.py).

    Spawn requests COALESCE: concurrent callers (the spawn thread pool
    during a storm or a batched lease) enqueue their request and one of
    them — whoever wins the pipe lock — ships every pending request as a
    single batched {"spawn": [...]} line, so the zygote forks K children
    per select wakeup instead of one pipe round trip per worker.  A lone
    caller degenerates to the old one-request/one-reply exchange cost."""

    def __init__(self, env: dict, batch_max: int = 8):
        import threading
        self._lock = threading.Lock()        # pipe ownership
        self._qlock = threading.Lock()       # pending-request queue
        self._pending: list = []             # [req, Event, pid, exc]
        self.batch_max = max(1, batch_max)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_zygote"],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        import json as _json
        line = self.proc.stdout.readline()  # waits for {"ready": true}
        if not line or not _json.loads(line).get("ready"):
            raise RuntimeError("zygote failed to start")

    def spawn(self, argv: list, env: dict, stdout: str, stderr: str) -> int:
        import threading
        item = [{"argv": argv, "env": env, "stdout": stdout,
                 "stderr": stderr}, threading.Event(), None, None]
        with self._qlock:
            self._pending.append(item)
        while not item[1].is_set():
            # Whoever holds the pipe flushes EVERYONE's pending requests;
            # the rest block here until their reply (or help flush the
            # next wave once the pipe frees up).
            if not self._lock.acquire(timeout=0.05):
                continue
            try:
                if item[1].is_set():
                    break
                with self._qlock:
                    batch = self._pending[:self.batch_max]
                    del self._pending[:len(batch)]
                if batch:
                    self._spawn_batch(batch)
            finally:
                self._lock.release()
        if item[3] is not None:
            raise item[3]
        return item[2]

    def _spawn_batch(self, batch: list) -> None:
        """Ship one batched fork request; runs under self._lock."""
        import json as _json
        line = None
        exc = None
        try:
            self.proc.stdin.write((_json.dumps(
                {"spawn": [it[0] for it in batch]}) + "\n").encode())
            self.proc.stdin.flush()
            line = self.proc.stdout.readline()
        except Exception as e:  # noqa: BLE001 - fanned to every waiter
            exc = e
        if exc is None and not line:
            exc = RuntimeError("zygote died")
        if exc is None:
            pids = _json.loads(line).get("pids", [])
            if len(pids) != len(batch):
                exc = RuntimeError("zygote spawn reply shape mismatch")
        for i, it in enumerate(batch):
            if exc is not None:
                it[3] = exc
            else:
                it[2] = int(pids[i])
            it[1].set()

    def poll_exits(self, into: dict) -> None:
        """Drain the zygote's reap reports into `into` ({pid: code})."""
        import json as _json
        with self._lock:
            self.proc.stdin.write(b'{"reap": true}\n')
            self.proc.stdin.flush()
            line = self.proc.stdout.readline()
        if not line:
            raise RuntimeError("zygote died")
        for pid, code in _json.loads(line).get("exited", []):
            into[int(pid)] = int(code)

    def close(self):
        try:
            self.proc.stdin.close()
            self.proc.terminate()
        except Exception:
            pass


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, job_id: int,
                 env_hash: str = "", tpu: bool = False):
        self.proc = proc
        self.job_id = job_id
        self.env_hash = env_hash  # runtime-env cache key (worker_pool.h:156)
        self.tpu = tpu           # spawned with TPU runtime access
        self.worker_id: WorkerID | None = None
        self.address: str = ""
        self.native_port: int = 0  # worker's framed-TCP plane (taskrpc.cc)
        self.state = "starting"  # starting/idle/claimed/leased/actor
        self.reserved = False    # pinned for the lease that spawned it
        self.lease_id: str | None = None
        self.lease_resources: dict = {}
        self.lease_bundle: tuple | None = None  # (pg_hex, index) if in a PG
        self.actor_id = None
        self.idle_since = time.monotonic()
        self.leased_at = 0.0
        # Set via the WorkerExiting RPC when the worker announces a
        # deliberate exit (SIGTERM drain, preemption abort) so the reaper
        # reports intent instead of "crash" (reference: raylet
        # DisconnectClient carries a WorkerExitType).
        self.exit_reason: str | None = None
        self.log_paths: dict = {}
        self.log_offsets: dict = {}
        self.boot_span = None    # sched/worker_boot, closed by WorkerReady
        self.ready = asyncio.Event()


class NodeDaemon:
    def __init__(self, gcs_address: str, resources: dict | None = None,
                 store_capacity: int = 256 << 20, is_head: bool = False,
                 host: str = "127.0.0.1", session_dir: str = "/tmp/ray_tpu"):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self.gcs = GcsClient(gcs_address)
        self.pool = ClientPool()
        # Node incarnation (split-brain fencing): starts at 0, adopted
        # from the GCS's fencing verdict when this node re-registers
        # after having been declared dead — see _register_with_gcs.
        self.incarnation = 0
        # Last GCS boot id seen in get_nodes replies; a change means the
        # head restarted underneath us and owes an anti-entropy resync.
        self._gcs_boot_id: str | None = None
        self.host = host
        self.is_head = is_head
        self.session_dir = session_dir
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        # This daemon's own black box lands with the worker dumps so
        # collect_events finds every dead process's ring in one place.
        os.environ.setdefault("RAY_TPU_FLIGHTREC_DIR",
                              os.path.join(session_dir, "logs"))
        self.store_path = os.path.join(
            "/dev/shm", f"ray_tpu_{self.node_id.hex()[:12]}")
        self.store = ObjectStore.create(self.store_path, store_capacity)
        self.resources_total = dict(resources or detect_resources())
        self.resources_available = dict(self.resources_total)
        # Placement-group bundles reserved on this node:
        # (pg_hex, index) -> {"reserved": demand, "available": remaining,
        #                     "committed": bool}
        # (reference: raylet PlacementGroupResourceManager 2PC,
        #  placement_group_resource_manager.h:46)
        self.bundles: dict[tuple, dict] = {}
        self.workers: dict[int, WorkerHandle] = {}  # pid -> handle
        # Preemption notice state (simulated TPU maintenance event):
        # while `preempting`, every new lease / bundle prepare is rejected
        # with reason "preempting" so the scheduler spills to healthy
        # nodes, and `_preempt_victims` pins the pids alive at notice time
        # so the deadline kill can never hit a later-formed gang.
        self.preempting = False
        self._preempt_victims: set[int] = set()
        self._lease_seq = 0
        self.server = RpcServer(host)
        self._shutdown = asyncio.Event()
        self.max_workers = _cfg().max_workers_per_node or max(
            8, int(self.resources_total.get("CPU", 1)) * 4)
        # Startup throttling (reference: worker_pool.h:245 startup tokens /
        # maximum_startup_concurrency scales with host cores): concurrent
        # python spawns contend for cores — past this many in-flight
        # spawns, lease requests wait for an existing worker instead of
        # forking another interpreter.
        # Floor of 4: spawning is import-I/O heavy, and on small/cgroup-
        # restricted hosts (cpu_count()==1) a throttle of 1 serializes the
        # whole pool ramp-up behind one ~0.3s boot at a time.
        self.max_startup_concurrency = (
            _cfg().max_startup_concurrency or max(4, os.cpu_count() or 1))
        # Fork-server (worker_zygote.py): prestarted off-loop at daemon
        # start so its cold-import time never blocks a lease; until it's
        # ready, spawns fall back to the classic Popen path.
        self._zygote: _Zygote | None = None
        self._zygote_exits: dict = {}   # pid -> exit code (reap reports)
        # Process creation runs off-loop (see _spawn_worker); _spawning
        # counts in-executor spawns for the startup throttle.
        from concurrent.futures import ThreadPoolExecutor
        self._spawn_exec = ThreadPoolExecutor(
            max_workers=max(4, _cfg().zygote_spawn_parallelism),
            thread_name_prefix="spawn")
        self._spawning = 0
        self._spawn_seq = 0
        # Recent lease demand, (t, (job_id, env_hash, tpu)) — drives the
        # pre-warm pool (see _prewarm_tick): a storm's lease rate sizes
        # how many idle workers to keep forked ahead of the next wave.
        self._lease_demand: deque = deque(maxlen=512)
        self._capacity_freed: asyncio.Event | None = None  # made on start()
        # Parked lease waiters, FIFO: capacity events hand off to ONE
        # waiter (see _notify_capacity).
        self._worker_waiters: deque = deque()
        # Object spilling (reference: raylet LocalObjectManager
        # local_object_manager.h:41 + _private/external_storage.py:246
        # FileSystemStorage).  With spilling on, LRU eviction is disabled:
        # primary copies are written to disk under memory pressure and
        # restored on demand instead of destroyed.
        self.spill_enabled = _cfg().spill_enabled
        self.spill_dir = os.environ.get("RAY_TPU_SPILL_DIR") or os.path.join(
            session_dir, "spill", self.node_id.hex()[:12])
        self.spill_high = _cfg().spill_high_watermark
        self.spill_low = _cfg().spill_low_watermark
        self.spilled: dict[bytes, tuple[str, int]] = {}  # id -> (path, size)
        self.spilled_bytes = 0

    # ---------------- worker pool ----------------

    async def _spawn_worker(self, job_id: int,
                            runtime_env: dict | None = None,
                            tpu: bool = False) -> WorkerHandle:
        """Spawn a worker WITHOUT blocking the event loop: the zygote
        pipe round trip (or cold Popen) costs ~10ms of wall — measured
        at 12ms/spawn of loop stall during an actor storm — so the
        process-creation step runs in a small thread pool while the
        loop keeps serving leases, heartbeats and WorkerReady RPCs."""
        from ray_tpu._private import runtime_env as renv
        log_base = os.path.join(self.session_dir, "logs",
                                f"worker-{self._spawn_seq}-{os.getpid()}")
        self._spawn_seq += 1
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        # Chaos identity: the spawn ordinal salts the worker's fault
        # schedule so a killed worker's replacement doesn't replay the
        # draw that killed it (fault_injection.ChaosController).
        env["RAY_TPU_CHAOS_PROC_SALT"] = str(self._spawn_seq)
        # Flight-recorder black box: crash dumps land next to the worker
        # logs so CollectEvents / state.events() can stitch a dead
        # worker's ring with live peers.
        env["RAY_TPU_FLIGHTREC_DIR"] = os.path.join(
            self.session_dir, "logs")
        if not tpu:
            # Leases without a TPU demand get a worker that skips runtime
            # TPU registration (the site hook imports jax + the PJRT plugin
            # — ~2s of the ~2.3s worker boot).  Non-TPU workers boot in
            # ~0.3s, and user jax code in them falls back to host CPU.
            # Forced unconditionally (not just for the axon plugin): on a
            # standard PJRT host an unset/"tpu" JAX_PLATFORMS would still
            # auto-init the TPU runtime and could seize exclusive-access
            # chips away from TPU-leased workers.  A runtime_env env_vars
            # override below still wins (applied after this).
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        if runtime_env:
            import json as _json
            env.update(runtime_env.get("env_vars", {}))
            env["RAY_TPU_RUNTIME_ENV"] = _json.dumps(runtime_env)
            env["RAY_TPU_RUNTIME_ENV_CACHE"] = os.path.join(
                self.session_dir, "runtime_env")
        argv = ["--gcs", self.gcs_address,
                "--hostd", f"{self.host}:{self.server.port}",
                "--store", self.store_path,
                "--node-id", self.node_id.hex(),
                "--job-id", str(job_id)]
        self._spawning += 1
        # Spawn-path attribution (actor_storm mode in scale_attrib.py):
        # zygote_fork covers process creation (fork round trip or cold
        # Popen), worker_boot the child's interpreter/runtime ramp until
        # its WorkerReady lands.
        ftok = spans.begin("sched", "zygote_fork",
                           cold=self._zygote is None or tpu)
        try:
            proc = await asyncio.get_running_loop().run_in_executor(
                self._spawn_exec, self._make_proc, argv, env, log_base,
                tpu)
        finally:
            self._spawning -= 1
            spans.end(ftok)
        handle = WorkerHandle(proc, job_id, renv.env_hash(runtime_env), tpu)
        handle.boot_span = spans.begin("sched", "worker_boot",
                                       pid=proc.pid)
        handle.log_paths = {"stdout": log_base + ".out",
                            "stderr": log_base + ".err"}
        handle.log_offsets = {"stdout": 0, "stderr": 0}
        _metrics()["workers_spawned"].inc()
        self.workers[proc.pid] = handle
        logger.info("spawned worker pid=%d job=%d env=%s", proc.pid, job_id,
                    handle.env_hash or "-")
        return handle

    def _make_proc(self, argv, env, log_base, tpu):
        """Blocking process creation — runs on the spawn thread pool."""
        proc = None
        if not tpu and _cfg().worker_zygote:
            # Fast path: fork the pre-imported template (~1-2ms vs ~300ms
            # cold spawn).  TPU workers never fork — PJRT state must not
            # cross a fork.
            try:
                pid = self._zygote_spawn(
                    argv, env, log_base + ".out", log_base + ".err")
                if pid is not None:
                    proc = _ForkedProc(pid, self._zygote_exits)
            except Exception:
                logger.exception("zygote spawn failed; cold-spawning")
                # Same rule as the reap poll: never kill a live zygote —
                # its death would cascade to every forked worker.
                if (self._zygote is not None
                        and self._zygote.proc.poll() is not None):
                    self._zygote_close()
        if proc is None:
            cmd = [sys.executable, "-m", "ray_tpu._private.worker_main",
                   *argv]
            out = open(log_base + ".out", "ab")
            err = open(log_base + ".err", "ab")
            proc = subprocess.Popen(cmd, env=env, stdout=out, stderr=err)
        return proc

    def _zygote_spawn(self, argv, env, out_path, err_path) -> int | None:
        """Fork via the prestarted zygote; None while it's still warming
        (caller cold-spawns instead of waiting)."""
        if self._zygote is None:
            self._prestart_zygote()
            return None
        return self._zygote.spawn(argv, env, out_path, err_path)

    def _prestart_zygote(self):
        if getattr(self, "_zygote_starting", False):
            return
        self._zygote_starting = True

        def _boot():
            try:
                zenv = dict(os.environ)
                zenv.pop("PALLAS_AXON_POOL_IPS", None)
                zenv["JAX_PLATFORMS"] = "cpu"
                self._zygote = _Zygote(
                    zenv, batch_max=_cfg().zygote_spawn_parallelism)
            except Exception:
                logger.exception("zygote failed to start; cold spawns only")
            finally:
                self._zygote_starting = False

        import threading
        threading.Thread(target=_boot, daemon=True,
                         name="zygote-boot").start()

    def _zygote_close(self):
        if self._zygote is not None:
            self._zygote.close()
            self._zygote = None

    async def worker_ready(self, req):
        """Called by a freshly started worker process."""
        handle = self.workers.get(req["pid"])
        if handle is None:
            return {"ok": False}
        handle.worker_id = req["worker_id"]
        handle.address = req["address"]
        handle.native_port = req.get("native_port", 0)
        handle.state = "idle"
        handle.idle_since = time.monotonic()
        if handle.boot_span is not None:
            spans.end(handle.boot_span)
            handle.boot_span = None
        handle.ready.set()
        # Wake lease requests parked behind the startup throttle.
        self._notify_capacity()
        return {"ok": True, "node_id": self.node_id}

    async def _get_worker(self, job_id: int, timeout: float = 60.0,
                          runtime_env: dict | None = None,
                          tpu: bool = False):
        """Pop an idle worker for (job, runtime-env hash, tpu-ness),
        spawning if necessary.  The returned handle is already claimed
        (state="claimed") so concurrent leases can never share a worker."""
        from ray_tpu._private import runtime_env as renv
        want_hash = renv.env_hash(runtime_env)
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            for handle in self.workers.values():
                if handle.state == "idle" and not handle.reserved \
                        and handle.job_id == job_id \
                        and handle.env_hash == want_hash \
                        and handle.tpu == tpu:
                    handle.state = "claimed"
                    return handle
            # No liveness syscalls here: this scan runs hundreds of times
            # per storm, and a kill(pid, 0) per handle per pass measured
            # ~4ms/actor.  `returncode` is refreshed by the reaper sweep
            # (and by anyone who polls); a just-died worker counts live
            # for <1 sweep, which only makes the throttle conservative.
            live = [w for w in self.workers.values()
                    if w.proc.returncode is None]
            starting = sum(1 for w in live if w.state == "starting") \
                + self._spawning
            # Forked (zygote) spawns skip the interpreter+import cost, so
            # the anti-thundering-herd throttle — which exists because
            # cold spawns contend for cores — opens up for them.  Only
            # when the zygote is actually SERVING: while it's still
            # warming (or failed), spawns are cold Popens and must keep
            # the cold throttle.
            throttle = self.max_startup_concurrency
            if not tpu and self._zygote is not None:
                throttle = max(throttle, 32)
            if starting >= throttle:
                # Throttle check comes BEFORE eviction: only kill an idle
                # worker when a replacement spawn will actually follow.
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    return None
                await self._wait_worker_slot(remaining)
                continue
            if len(live) >= self.max_workers:
                # Evict an idle worker that can't serve this lease — other
                # job OR same job with a different runtime-env hash.
                for handle in live:
                    if handle.state == "idle" and not handle.reserved \
                            and (handle.job_id != job_id
                                 or handle.env_hash != want_hash
                                 or handle.tpu != tpu):
                        self._kill_worker(handle)
                        break
                else:
                    return None
            # Spawn a worker pinned to this lease (reserved=True) so another
            # lease cannot steal it the moment it boots — stealing cascades
            # into one extra spawn per steal.
            handle = await self._spawn_worker(job_id, runtime_env, tpu)
            handle.reserved = True
            try:
                await asyncio.wait_for(
                    handle.ready.wait(),
                    max(0.1, deadline - asyncio.get_event_loop().time()))
            except asyncio.TimeoutError:
                self._kill_worker(handle)
                return None
            handle.reserved = False
            handle.state = "claimed"
            return handle

    async def _escalate_kill(self, proc, grace: float | None = None):
        """Bounded SIGTERM -> wait -> SIGKILL escalation.

        SIGTERM can be ignored or deferred by native code (TPU runtime,
        compiled extensions) and by the worker's own graceful-exit drain;
        polling every 50ms keeps detection prompt while the grace window
        (worker_sigterm_grace_s) bounds how long a stuck child can wedge
        teardown before SIGKILL ends it unconditionally."""
        if grace is None:
            grace = _cfg().worker_sigterm_grace_s
        deadline = time.monotonic() + max(0.0, grace)
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return
            await asyncio.sleep(0.05)
        if proc.poll() is None:
            try:
                proc.kill()
            except Exception:
                pass

    def _kill_worker(self, handle: WorkerHandle):
        self.workers.pop(handle.proc.pid, None)
        if handle.proc.poll() is None:
            handle.proc.terminate()
            try:
                asyncio.ensure_future(self._escalate_kill(handle.proc))
            except RuntimeError:
                pass  # no running loop (teardown path escalates itself)

    # ---------------- leasing ----------------

    def _reserve(self, demand: dict) -> bool:
        for k, v in demand.items():
            if v > 0 and self.resources_available.get(k, 0.0) + 1e-9 < v:
                return False
        for k, v in demand.items():
            if v > 0:
                self.resources_available[k] = self.resources_available.get(k, 0.0) - v
        return True

    def _unreserve(self, demand: dict):
        for k, v in demand.items():
            if v > 0:
                self.resources_available[k] = min(
                    self.resources_available.get(k, 0.0) + v,
                    self.resources_total.get(k, float("inf")))
        self._notify_capacity()

    def _notify_capacity(self, n: int | None = None):
        if self._capacity_freed is not None:
            self._capacity_freed.set()
            self._capacity_freed = asyncio.Event()
        # Hand freed workers/slots to as many parked leases as current
        # capacity can plausibly satisfy in ONE pass — under batched
        # grants a single release can unblock several small leases, and
        # a one-baton handoff serialized them a release event apart.
        # Still bounded: broadcasting to EVERY parked waiter is
        # O(waiters x workers) per event — the measured collapse mode of
        # a 1,000-actor storm (each ready wakes 1,000 leases, each
        # rescanning 1,000 handles) — so the wake count is capped by
        # idle workers plus startup-throttle headroom (a woken waiter
        # that can't use the slot re-parks, which self-limits).
        if not self._worker_waiters:
            return
        if n is None:
            idle = sum(1 for w in self.workers.values()
                       if w.state == "idle" and not w.reserved)
            starting = sum(1 for w in self.workers.values()
                           if w.state == "starting") + self._spawning
            headroom = self.max_startup_concurrency - starting
            n = max(1, idle + max(0, headroom))
        while self._worker_waiters and n > 0:
            fut = self._worker_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                n -= 1

    async def _wait_capacity(self, timeout: float):
        if self._capacity_freed is None:
            self._capacity_freed = asyncio.Event()
        ev = self._capacity_freed
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    async def _wait_worker_slot(self, timeout: float):
        """Park until ONE capacity event is handed to us (FIFO), with a
        bounded nap as a backstop — both for lost wakeups and for the
        baton landing on a waiter that can't use the freed slot (a
        tpu/runtime-env mismatch re-parks without passing it on; the
        1s cap bounds that added latency).  Callers re-check their
        condition in a loop either way."""
        fut = asyncio.get_running_loop().create_future()
        self._worker_waiters.append(fut)
        try:
            await asyncio.wait_for(fut, min(timeout, 1.0))
        except asyncio.TimeoutError:
            pass
        finally:
            if not fut.done():
                fut.cancel()

    def _bundle_reserve(self, bundle_key: tuple, demand: dict) -> bool:
        """Charge a lease against a committed bundle's remaining capacity."""
        b = self.bundles.get(bundle_key)
        if b is None or not b["committed"]:
            return False
        avail = b["available"]
        for k, v in demand.items():
            if v > 0 and avail.get(k, 0.0) + 1e-9 < v:
                return False
        for k, v in demand.items():
            if v > 0:
                avail[k] = avail.get(k, 0.0) - v
        return True

    def _bundle_unreserve(self, bundle_key: tuple, demand: dict):
        b = self.bundles.get(bundle_key)
        if b is None:  # PG removed while the lease was out; nothing to refund
            return
        for k, v in demand.items():
            if v > 0:
                b["available"][k] = min(
                    b["available"].get(k, 0.0) + v, b["reserved"].get(k, v))
        self._notify_capacity()

    def _release_lease(self, handle: "WorkerHandle"):
        if handle.lease_bundle is not None:
            self._bundle_unreserve(handle.lease_bundle,
                                   handle.lease_resources)
        else:
            self._unreserve(handle.lease_resources)
        handle.lease_resources = {}
        handle.lease_bundle = None

    async def lease_worker(self, req):
        """Lease a worker for normal task execution; queues while the node is
        saturated (reference: RequestWorkerLease node_manager.proto:363 +
        LocalTaskManager dispatch queue).  With req["bundle"]=(pg_hex, idx)
        the demand is charged against that placement-group bundle."""
        if self.preempting:
            events.record("sched", "lease_reject", reason="preempting")
            return {"granted": False, "reason": "preempting"}
        demand = req.get("resources", {})
        bundle = tuple(req["bundle"]) if req.get("bundle") else None
        job_id = req.get("job_id", 0)
        # Batched grants: the request carries how many same-key leases the
        # driver's queue wants; grant as many as this node can satisfy
        # RIGHT NOW in one reply (parking only while it can grant zero).
        # Worker acquisition for a multi-grant runs concurrently, so N
        # cold spawns coalesce into one batched zygote fork.
        count = max(1, int(req.get("count", 1)))
        tpu = _wants_tpu(demand)
        self._note_lease_demand(job_id, req.get("runtime_env"), tpu, count)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + req.get("queue_timeout", 10.0)
        grants: list[WorkerHandle] = []
        while True:
            k = 0
            while len(grants) + k < count:
                reserved = (self._bundle_reserve(bundle, demand) if bundle
                            else self._reserve(demand))
                if not reserved:
                    break
                k += 1
            if k:
                handles = await asyncio.gather(*[
                    self._get_worker(job_id,
                                     runtime_env=req.get("runtime_env"),
                                     tpu=tpu)
                    for _ in range(k)])
                for handle in handles:
                    if handle is not None:
                        grants.append(handle)
                        continue
                    if bundle:
                        self._bundle_unreserve(bundle, demand)
                    else:
                        self._unreserve(demand)
                if not grants and not any(
                        w.state == "idle" or w.proc.poll() is None
                        for w in self.workers.values()):
                    events.record("sched", "lease_reject",
                                  reason="no_worker")
                    return {"granted": False, "reason": "no_worker"}
            elif not grants and bundle and bundle not in self.bundles:
                events.record("sched", "lease_reject", reason="no_bundle")
                return {"granted": False, "reason": "no_bundle"}
            if grants:
                # Partial fills return immediately: the driver re-pumps
                # for the remainder; holding granted workers hostage to
                # the stragglers would idle them for the parking window.
                break
            remaining = deadline - loop.time()
            if remaining <= 0:
                events.record("sched", "lease_reject", reason="busy",
                              demand=demand)
                return {"granted": False, "reason": "busy"}
            await self._wait_worker_slot(remaining)
        # Chain wake: capacity may remain (fractional demand) — pass the
        # baton to the next parked leases instead of broadcasting.
        self._notify_capacity()
        out = []
        for handle in grants:
            self._lease_seq += 1
            _metrics()["leases_granted"].inc()
            lease_id = f"{self.node_id.hex()[:8]}-{self._lease_seq}"
            handle.leased_at = time.monotonic()
            handle.state = "leased"
            handle.lease_id = lease_id
            handle.lease_resources = dict(demand)
            handle.lease_bundle = bundle
            out.append({"worker_address": handle.address,
                        "native_port": handle.native_port,
                        "lease_id": lease_id, "node_id": self.node_id})
        logger.info("lease %s -> %d worker(s), head pid=%d", out[0]["lease_id"],
                    len(out), grants[0].proc.pid)
        events.record("sched", "lease_grant", lease_id=out[0]["lease_id"],
                      pid=grants[0].proc.pid, granted=len(out),
                      requested=count)
        reply = dict(out[0])
        reply["granted"] = True
        reply["grants"] = out
        return reply

    async def return_worker(self, req):
        for handle in self.workers.values():
            if handle.lease_id == req["lease_id"]:
                self._release_lease(handle)
                logger.info("return lease %s pid=%d", req["lease_id"], handle.proc.pid)
                handle.lease_id = None
                if req.get("kill") or handle.proc.poll() is not None:
                    self._kill_worker(handle)
                else:
                    handle.state = "idle"
                    handle.idle_since = time.monotonic()
                return {"ok": True}
        return {"ok": False}

    async def lease_worker_for_actor(self, req):
        """Dedicated worker for an actor (reference: GcsActorScheduler
        leases via the same raylet path, gcs_actor_scheduler.h:111).

        QUEUES while the node is saturated, like lease_worker: an actor
        storm must drain at worker-spawn speed, not convert transient
        saturation into rejections the GCS spins its placement-attempt
        budget against (reference: leases wait in the raylet's dispatch
        queue until resources and a worker exist)."""
        if self.preempting:
            aid = req.get("actor_id")
            events.record("sched", "lease_reject", reason="preempting",
                          actor=getattr(aid, "hex", lambda: aid)())
            return {"granted": False, "reason": "preempting"}
        demand = req.get("resources", {})
        bundle = tuple(req["bundle"]) if req.get("bundle") else None
        self._note_lease_demand(req.get("job_id", 0),
                                req.get("runtime_env"),
                                _wants_tpu(demand))
        loop = asyncio.get_running_loop()
        deadline = loop.time() + req.get("queue_timeout", 30.0)
        while True:
            if bundle:
                reserved = self._bundle_reserve(bundle, demand)
                if not reserved and bundle not in self.bundles:
                    return {"granted": False, "reason": "no_bundle"}
            else:
                reserved = self._reserve(demand)
            if reserved:
                handle = await self._get_worker(
                    req.get("job_id", 0),
                    runtime_env=req.get("runtime_env"),
                    tpu=_wants_tpu(demand))
                if handle is not None:
                    break
                if bundle:
                    self._bundle_unreserve(bundle, demand)
                else:
                    self._unreserve(demand)
            remaining = deadline - loop.time()
            if remaining <= 0:
                return {"granted": False, "reason": "busy"}
            await self._wait_worker_slot(remaining)
        self._notify_capacity()   # chain wake: see lease_worker
        actor_id = req["actor_id"]
        events.record("sched", "lease_grant",
                      actor=getattr(actor_id, "hex", lambda: actor_id)(),
                      pid=handle.proc.pid)
        handle.state = "actor"
        handle.actor_id = req["actor_id"]
        handle.lease_resources = demand
        handle.lease_bundle = bundle
        return {"granted": True, "worker_address": handle.address,
                "native_port": handle.native_port,
                "node_id": self.node_id}

    # ---------------- placement-group bundles (2PC) ----------------
    # Reference: node_manager.proto:378 PrepareBundleResources /
    # :382 CommitBundleResources / CancelResourceReserve + raylet
    # placement_group_resource_manager.h:46.

    async def prepare_bundle(self, req):
        if self.preempting:
            # A doomed node must not accept new gang reservations during
            # its grace window: the PG would commit and immediately die.
            return {"ok": False, "reason": "preempting"}
        key = (req["pg_id"], req["index"])
        demand = req["resources"]
        if key in self.bundles:
            return {"ok": True}  # idempotent re-prepare
        if not self._reserve(demand):
            return {"ok": False, "reason": "resources"}
        self.bundles[key] = {"reserved": dict(demand),
                             "available": dict(demand), "committed": False}
        return {"ok": True}

    async def commit_bundle(self, req):
        b = self.bundles.get((req["pg_id"], req["index"]))
        if b is None:
            return {"ok": False}
        b["committed"] = True
        return {"ok": True}

    async def cancel_bundle(self, req):
        """Release one bundle (or all bundles of a PG when index is None).
        Workers leased against the bundle are killed — their resources were
        the bundle's (reference: raylet kills PG workers on removal)."""
        pg_id = req["pg_id"]
        index = req.get("index")
        keys = [k for k in self.bundles
                if k[0] == pg_id and (index is None or k[1] == index)]
        for key in keys:
            for handle in list(self.workers.values()):
                if handle.lease_bundle == key:
                    handle.lease_resources = {}
                    handle.lease_bundle = None
                    self._kill_worker(handle)
            b = self.bundles.pop(key)
            self._unreserve(b["reserved"])
        return {"ok": True, "released": len(keys)}

    # ---------------- object transfer ----------------

    async def pull_object(self, req):
        """Read an object out of the local store for a remote node.  With
        req["max_inline"], larger objects reply {"too_large", data_size,
        metadata} and the caller switches to the chunk protocol — small
        objects (the common case) stay one round trip."""
        from ray_tpu._private.ids import ObjectID
        max_inline = req.get("max_inline")
        buf = self.store.get(ObjectID(req["id"]), timeout_ms=int(
            req.get("timeout_ms", 0)))
        if buf is None:
            spilled = self._spilled_meta(req["id"])
            if spilled is None:
                return {"found": False}
            data_size, metadata = spilled
            if max_inline is not None and data_size > max_inline:
                return {"found": True, "too_large": True,
                        "data_size": data_size, "metadata": metadata}
            restored = self._read_spilled(req["id"])
            if restored is None:
                return {"found": False}
            _metrics()["objects_restored"].inc()
            data, metadata = restored
            return {"found": True, "data": data, "metadata": metadata,
                    "spilled": True}
        try:
            if max_inline is not None and len(buf.data) > max_inline:
                xfer = getattr(self, "transfer_server", None)
                return {"found": True, "too_large": True,
                        "data_size": len(buf.data),
                        "metadata": buf.metadata,
                        "transfer_port":
                            xfer.port if xfer is not None else None}
            return {"found": True, "data": bytes(buf.data),
                    "metadata": buf.metadata}
        finally:
            buf.release()

    async def pull_object_meta(self, req):
        """Size/metadata probe for the chunked pull path (reference:
        object_manager chunked transfer: ObjectBufferPool chunk layout).
        Accepts the typed contract (protocol.pb.PullObjectMetaRequest) or
        the legacy dict, replying in kind."""
        from ray_tpu import protocol
        from ray_tpu._private.ids import ObjectID
        typed = protocol.is_message(req)
        id_binary = req.id if typed else req["id"]
        oid = ObjectID(id_binary)
        xfer = getattr(self, "transfer_server", None)
        xfer_port = xfer.port if xfer is not None else None

        def reply(found, data_size=0, metadata=b"", spilled=False,
                  port=None):
            if typed:
                return protocol.pb.PullObjectMetaReply(
                    found=found, data_size=data_size, metadata=metadata,
                    spilled=spilled, transfer_port=port or 0)
            return {"found": found, "data_size": data_size,
                    "metadata": metadata, "spilled": spilled,
                    "transfer_port": port}

        buf = self.store.get(oid, timeout_ms=0)
        if buf is not None:
            try:
                return reply(True, len(buf.data), buf.metadata, False,
                             xfer_port)
            finally:
                buf.release()
        spilled = self._spilled_meta(id_binary)
        if spilled is None:
            return reply(False)
        data_size, meta = spilled
        # Spilled payloads live on disk, not in the shm segment — the
        # native plane can't serve them; the puller stays on chunk RPCs.
        return reply(True, data_size, meta, True)

    async def pull_object_chunk(self, req):
        """One chunk of an object's payload (reference: push_manager.h
        chunked pushes with in-flight throttling — here the PULLER
        throttles).  Typed (PullObjectChunkRequest) or legacy dict."""
        from ray_tpu import protocol
        from ray_tpu._private.ids import ObjectID
        typed = protocol.is_message(req)
        if typed:
            id_binary, offset, length = req.id, req.offset, req.length
        else:
            id_binary, offset, length = req["id"], req["offset"], \
                req["length"]

        def reply(found, data=b""):
            if typed:
                return protocol.pb.PullObjectChunkReply(found=found,
                                                        data=data)
            return {"found": found, "data": data}

        buf = self.store.get(ObjectID(id_binary), timeout_ms=0)
        if buf is not None:
            try:
                return reply(True, bytes(buf.data[offset:offset + length]))
            finally:
                buf.release()
        chunk = self._read_spilled_range(id_binary, offset, length)
        if chunk is None:
            return reply(False)
        return reply(True, chunk)

    async def push_object(self, req):
        from ray_tpu import protocol
        from ray_tpu._private.ids import ObjectID
        typed = protocol.is_message(req)
        if typed:
            id_binary, data, metadata = req.id, req.data, req.metadata
        else:
            id_binary, data, metadata = req["id"], req["data"], \
                req.get("metadata", b"")
        oid = ObjectID(id_binary)
        if not self.store.contains(oid):
            try:
                self.store.put_bytes(oid, data, metadata)
            except Exception as e:  # duplicate create race is fine
                logger.debug("push_object: %s", e)
        return protocol.pb.PushObjectReply(ok=True) if typed \
            else {"ok": True}

    async def free_object(self, req):
        from ray_tpu._private.ids import ObjectID
        self.store.delete(ObjectID(req["id"]))
        self._drop_spilled(req["id"])
        return {"ok": True}

    async def free_objects(self, req):
        """Batched form: owners buffer freed ids and flush one RPC
        (reference: raylet FreeObjects batches plasma deletions)."""
        from ray_tpu._private.ids import ObjectID
        for id_binary in req["ids"]:
            self.store.delete(ObjectID(id_binary))
            self._drop_spilled(id_binary)
        return {"ok": True}

    async def store_stats(self, req):
        stats = self.store.stats()
        stats["spilled_objects"] = len(self.spilled)
        stats["spilled_bytes"] = self.spilled_bytes
        return stats

    # ---------------- worker log streaming ----------------

    def _collect_worker_log_lines(self, handle, final: bool = False):
        """New COMPLETE lines from a worker's log files.  Only consumes up
        to the last newline so a line straddling the read boundary (or a
        mid-write flush) is never split — unless `final` (worker dead:
        loop to EOF and flush everything, including a trailing partial
        line).  Returns (lines, undo) where undo restores the offsets if
        the publish fails (lines must not be lost to a GCS blip)."""
        lines = []
        undo = []
        for stream, path in handle.log_paths.items():
            prev = handle.log_offsets[stream]
            consumed = 0
            try:
                with open(path, "rb") as f:
                    f.seek(prev)
                    while True:
                        chunk = f.read(256 * 1024)
                        if not chunk:
                            break
                        if not final:
                            cut = chunk.rfind(b"\n")
                            if cut < 0:
                                break  # no complete line yet
                            chunk = chunk[:cut + 1]
                        consumed += len(chunk)
                        for raw in chunk.decode(
                                "utf-8", "replace").splitlines():
                            lines.append({"pid": handle.proc.pid,
                                          "job_id": handle.job_id,
                                          "stream": stream, "line": raw})
                        if not final:
                            break  # one bounded read per tick
            except OSError:
                continue
            if consumed:
                handle.log_offsets[stream] = prev + consumed
                undo.append((handle, stream, prev))
        return lines, undo

    async def _publish_log_lines(self, lines: list, undo: list) -> None:
        if not lines:
            return
        try:
            await self.gcs.call("Gcs", "add_log_lines", {"lines": lines})
        except Exception:
            # Rewind so the next tick re-reads — a GCS blip must not
            # create silent gaps in the stream.
            for handle, stream, prev in undo:
                handle.log_offsets[stream] = prev

    async def _log_tail_loop(self):
        """Tail worker stdout/stderr into the GCS log channel (reference:
        _private/log_monitor.py -> GCS pubsub -> driver echo)."""
        while True:
            await asyncio.sleep(0.5)
            lines = []
            undo = []
            for handle in list(self.workers.values()):
                ls, ud = self._collect_worker_log_lines(handle)
                lines.extend(ls)
                undo.extend(ud)
            await self._publish_log_lines(lines, undo)

    # ---------------- memory monitor ----------------

    @staticmethod
    def _read_memory_fraction() -> float:
        """Node memory usage fraction from /proc/meminfo (reference:
        common/memory_monitor.cc cgroup/system probing)."""
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2:
                        info[parts[0].rstrip(":")] = int(parts[1])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", 0)
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    def _pick_oom_victim(self):
        """Newest leased task worker first (its task is retriable), then
        newest actor worker (restartable per policy) — reference:
        raylet/worker_killing_policy.cc retriable-LIFO."""
        leased = [w for w in self.workers.values()
                  if w.state == "leased" and w.proc.poll() is None]
        if leased:
            return max(leased, key=lambda w: w.leased_at)
        actors = [w for w in self.workers.values()
                  if w.state == "actor" and w.proc.poll() is None]
        if actors:
            return max(actors, key=lambda w: w.leased_at)
        return None

    async def _memory_monitor_loop(self):
        while True:
            interval = _cfg().memory_monitor_interval_s
            await asyncio.sleep(interval)
            try:
                threshold = _cfg().memory_usage_threshold
                frac = self._read_memory_fraction()
                if frac < threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                logger.error(
                    "node memory at %.0f%% (threshold %.0f%%): killing "
                    "worker pid=%d to relieve pressure", frac * 100,
                    threshold * 100, victim.proc.pid)
                _metrics()["oom_workers_killed"].inc()
                self._release_lease(victim)
                # _kill_worker already schedules the bounded
                # SIGTERM -> wait -> SIGKILL escalation (_escalate_kill);
                # the old one-shot 2s poll here could miss a worker whose
                # native code ignored SIGTERM and raced the poll.
                self._kill_worker(victim)
                # Cooldown: give the kernel time to reclaim before judging
                # again — otherwise one spike serially destroys the node.
                await asyncio.sleep(max(3 * interval, 2.0))
            except Exception:
                logger.exception("memory monitor pass failed")

    # ---------------- spilling ----------------

    def _spill_some(self, bytes_needed: int = 0) -> int:
        """Spill sealed, unreferenced objects (oldest LRU first) until
        usage is under the low watermark (plus any immediate need)."""
        stats = self.store.stats()
        used, cap = stats["used"], stats["capacity"]
        goal = self.spill_low * cap
        if bytes_needed:
            goal = min(goal, cap - min(bytes_needed, cap))
        if used <= (self.spill_high * cap if not bytes_needed else goal):
            return 0
        os.makedirs(self.spill_dir, exist_ok=True)
        from ray_tpu.util import spans
        tok = spans.begin("object", "spill", store_used=used)
        freed = 0
        count = 0
        for oid, size, refcount, sealed, _tick in self.store.list_objects():
            if used - freed <= goal:
                break
            if not sealed or refcount != 0:
                continue
            if oid.binary() in self.spilled:
                continue
            buf = self.store.get(oid, timeout_ms=0)
            if buf is None:
                continue
            path = os.path.join(self.spill_dir, oid.hex())
            try:
                meta = bytes(buf.metadata) if buf.metadata else b""
                data = bytes(buf.data)
            finally:
                buf.release()
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(len(meta).to_bytes(8, "little"))
                f.write(meta)
                f.write(data)
            os.replace(tmp, path)
            self.spilled[oid.binary()] = (path, size)
            self.spilled_bytes += size
            _metrics()["objects_spilled"].inc()
            _metrics()["bytes_spilled"].inc(size)
            self.store.delete(oid)
            freed += size
            count += 1
        spans.end(tok, freed=freed, objects=count)
        if freed:
            logger.info("spilled %d bytes (%d objects on disk)", freed,
                        len(self.spilled))
        return freed

    def _read_spilled(self, id_binary: bytes):
        ent = self.spilled.get(id_binary)
        if ent is None:
            return None
        path, _size = ent
        try:
            with open(path, "rb") as f:
                meta_len = int.from_bytes(f.read(8), "little")
                meta = f.read(meta_len)
                data = f.read()
            return data, meta
        except FileNotFoundError:
            return None

    def _spilled_meta(self, id_binary: bytes):
        """(data_size, metadata) without reading the payload."""
        ent = self.spilled.get(id_binary)
        if ent is None:
            return None
        path, _size = ent
        try:
            total = os.path.getsize(path)
            with open(path, "rb") as f:
                meta_len = int.from_bytes(f.read(8), "little")
                meta = f.read(meta_len)
            return total - 8 - meta_len, meta
        except OSError:
            return None

    def _read_spilled_range(self, id_binary: bytes, offset: int,
                            length: int):
        """Seek+read one payload range — chunked pulls of spilled objects
        must not re-read the whole file per chunk."""
        ent = self.spilled.get(id_binary)
        if ent is None:
            return None
        path, _size = ent
        try:
            with open(path, "rb") as f:
                meta_len = int.from_bytes(f.read(8), "little")
                f.seek(8 + meta_len + offset)
                return f.read(length)
        except OSError:
            return None

    def _drop_spilled(self, id_binary: bytes):
        ent = self.spilled.pop(id_binary, None)
        if ent is not None:
            self.spilled_bytes -= ent[1]
            try:
                os.unlink(ent[0])
            except OSError:
                pass

    async def spill_objects(self, req):
        """Spill request from a worker whose put hit OOM (reference:
        raylet SpillObjects RPC, core_worker.proto:443).  Disk writes run
        in an executor thread — blocking the daemon loop would starve
        heartbeats and lease RPCs exactly when the node is under memory
        pressure."""
        if not self.spill_enabled:
            return {"freed": 0}
        loop = asyncio.get_running_loop()
        freed = await loop.run_in_executor(
            None, self._spill_some, req.get("bytes_needed", 0))
        return {"freed": freed}

    async def _spill_loop(self):
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(0.5)
            try:
                if self.spill_enabled:
                    await loop.run_in_executor(None, self._spill_some, 0)
            except Exception:
                logger.exception("spill sweep failed")

    async def get_metrics(self, req):
        """Node-level metric snapshot (reference: per-node agent scrape
        path, _private/metrics_agent.py): the daemon's own registry plus
        every live worker's, merged.  Application metrics live in worker
        processes (e.g. serve replica inference engines export prefix
        cache hit rates), so a hostd-only scrape would miss them.
        Worker probes run concurrently and failures are skipped — a
        wedged worker must not take down the node scrape."""
        from ray_tpu.util import metrics as mt
        _metrics()["store_used_bytes"].set(self.store.stats()["used"])
        merged = mt.collect()
        handles = [h for h in self.workers.values() if h.address]

        async def probe(handle):
            try:
                reply = await self.pool.get(handle.address).call(
                    "CoreWorker", "Metrics", {}, timeout=2)
                return reply.get("metrics") or {}
            except Exception:
                return {}

        for snap in await asyncio.gather(*[probe(h) for h in handles]):
            mt.merge_snapshot(merged, snap)
        return {"metrics": merged, "node_id": self.node_id.hex()}

    async def stack_traces(self, req):
        """Aggregate live thread stacks from this node's workers plus the
        daemon itself (reference: `ray stack` scripts.py:1798).  Worker
        probes run CONCURRENTLY: a node full of wedged workers — the very
        thing this exists to debug — must dump in ~one timeout, not N."""
        from ray_tpu._private.stack_dump import dump_state
        out = [{"pid": os.getpid(), "kind": "hostd", **dump_state()}]
        handles = [h for h in self.workers.values() if h.address]

        async def probe(handle):
            try:
                reply = await self.pool.get(handle.address).call(
                    "CoreWorker", "StackTrace", {}, timeout=5)
                return {"pid": reply["pid"], "kind": "worker",
                        "state": handle.state, "threads": reply["threads"],
                        "recent_events": reply.get("recent_events") or []}
            except Exception as e:
                return {"pid": handle.proc.pid, "kind": "worker",
                        "state": handle.state, "error": repr(e),
                        "threads": []}

        out.extend(await asyncio.gather(*[probe(h) for h in handles]))
        return {"processes": out}

    async def collect_stacks(self, req):
        """Live thread dumps from a SPECIFIC set of this node's workers
        (by pid) — the train hang watchdog's diagnosis RPC.  Unlike
        stack_traces this skips the daemon self-dump and probes only the
        gang's workers, concurrently: a wedged gang must dump in ~one
        probe timeout, not N."""
        pids = set(req.get("pids") or [])
        handles = [h for h in self.workers.values()
                   if h.address and (not pids or h.proc.pid in pids)]

        async def probe(handle):
            try:
                reply = await self.pool.get(handle.address).call(
                    "CoreWorker", "StackTrace", {}, timeout=5)
                return {"pid": reply["pid"], "state": handle.state,
                        "threads": reply["threads"],
                        "recent_events": reply.get("recent_events") or []}
            except Exception as e:
                return {"pid": handle.proc.pid, "state": handle.state,
                        "error": repr(e), "threads": []}

        return {"processes":
                await asyncio.gather(*[probe(h) for h in handles]),
                "node_id": self.node_id.hex()}

    async def collect_events(self, req):
        """Node-level flight-recorder scrape: the daemon's own ring, every
        live worker's ring (concurrent CollectEvents probes), and any
        crash dumps in the session log dir — the black boxes of processes
        that already died.  Each event gains pid/source; `now` rides
        along for cluster-wide clock-skew normalization."""
        since = float(req.get("since", 0.0))
        out = [dict(e, pid=os.getpid(), source="live")
               for e in events.snapshot(since=since)]
        handles = [h for h in self.workers.values() if h.address]

        async def probe(handle):
            try:
                reply = await self.pool.get(handle.address).call(
                    "CoreWorker", "CollectEvents", {"since": since},
                    timeout=5)
                return [dict(e, pid=reply["pid"], source="live")
                        for e in reply.get("events") or []]
            except Exception:
                return []

        for chunk in await asyncio.gather(*[probe(h) for h in handles]):
            out.extend(chunk)
        out.extend(e for e in
                   events.read_dumps(os.path.join(self.session_dir, "logs"))
                   if e["ts"] >= since)
        return {"events": out, "node_id": self.node_id.hex(),
                "now": time.time()}

    # ---------------- preemption (maintenance events) ----------------

    async def notify_preemption(self, req):
        """Advance notice that this host will be reclaimed in `grace_s`
        seconds (TPU maintenance event / spot preemption; in production
        wired to the metadata-server preemption signal, here driven by
        the chaos plane).  The daemon immediately stops granting leases
        and bundle reservations, fans the notice out to every live
        worker — train sessions there race a proactive checkpoint save
        against the window — and schedules the kill at the deadline."""
        grace = float(req.get("grace_s", _cfg().chaos_preempt_grace_s))
        if self.preempting:
            return {"ok": True, "already": True}
        self.preempting = True
        self._preempt_victims = {
            h.proc.pid for h in self.workers.values()
            if h.proc.poll() is None}
        _metrics()["preemption_notices"].inc()
        _metrics()["preemption_grace_s"].set(grace)
        logger.warning(
            "preemption notice: node %s reclaimed in %.1fs (%d workers "
            "notified)", self.node_id.hex()[:8], grace,
            len(self._preempt_victims))

        async def _notify(handle):
            try:
                await self.pool.get(handle.address).call(
                    "CoreWorker", "PreemptionNotice",
                    {"grace_s": grace}, timeout=2)
            except Exception:
                pass  # worker mid-exit; the deadline kill covers it

        targets = [h for h in list(self.workers.values())
                   if h.address and h.proc.poll() is None]
        if targets:
            await asyncio.gather(*[_notify(h) for h in targets])
        asyncio.ensure_future(self._preempt_kill(grace))
        return {"ok": True, "grace_s": grace}

    async def _preempt_kill(self, grace: float):
        """The reclaim at the end of the grace window.  A non-head node
        dies whole (os._exit, like a real preemption — the GCS health
        loop declares it dead and peers learn via node-watch).  A head
        node degrades to killing only the workers alive at notice time:
        the colocated GCS must survive so the cluster can re-form, which
        also keeps single-node chaos scenarios runnable."""
        await asyncio.sleep(max(0.0, grace))
        if not self.is_head:
            logger.warning("preemption: node %s reclaimed",
                           self.node_id.hex()[:8])
            os._exit(1)
        for pid in list(self._preempt_victims):
            handle = self.workers.get(pid)
            if handle is not None and handle.proc.poll() is None:
                self._kill_worker(handle)
        self._preempt_victims = set()
        self.preempting = False
        logger.warning("preemption: head %s lost its workers; leasing "
                       "re-enabled", self.node_id.hex()[:8])

    async def worker_exiting(self, req):
        """A worker announcing a deliberate exit (SIGTERM drain,
        preemption abort) before it dies, so the reaper reports intent
        instead of a crash and the owner's retry logic can tell a
        drained worker from a wedged one."""
        handle = self.workers.get(int(req.get("pid", 0)))
        if handle is None:
            return {"ok": False}
        handle.exit_reason = str(req.get("reason", "deliberate"))
        return {"ok": True}

    async def list_workers(self, req):
        """Per-node worker table for the state API (reference:
        experimental/state/api.py list_workers via raylet)."""
        out = []
        for handle in self.workers.values():
            out.append({
                "pid": handle.proc.pid,
                "worker_id": (handle.worker_id.hex()
                              if handle.worker_id else None),
                "state": handle.state,
                "job_id": handle.job_id,
                "address": handle.address,
                "lease_id": handle.lease_id,
                "lease_resources": dict(handle.lease_resources),
                "actor_id": (handle.actor_id.hex()
                             if handle.actor_id else None),
                "idle_s": round(time.monotonic() - handle.idle_since, 1)
                          if handle.state == "idle" else None,
                "alive": handle.proc.poll() is None,
            })
        return {"workers": out, "node_id": self.node_id.hex(),
                "store": self.store.stats(),
                "resources_total": dict(self.resources_total),
                "resources_available": dict(self.resources_available)}

    # ---------------- lifecycle ----------------

    async def shutdown_node(self, req):
        self._shutdown.set()
        return {"ok": True}

    def node_info(self) -> NodeInfo:
        import socket
        return NodeInfo(
            node_id=self.node_id,
            address=f"{self.host}:{self.server.port}",
            store_path=self.store_path,
            hostname=socket.gethostname(),
            resources_total=dict(self.resources_total),
            resources_available=dict(self.resources_available),
            is_head=self.is_head,
            incarnation=self.incarnation,
        )

    def _state_snapshot(self) -> dict:
        """Ground truth shipped with every (re-)register: what this node
        actually runs right now.  After a GCS restart the restored tables
        are a hypothesis; the anti-entropy reconcile trusts this instead
        (reference: raylet's RegisterNode piggybacks its live worker set
        on GCS restart via RayletNotifyGCSRestart)."""
        actors = []
        leased = 0
        for h in self.workers.values():
            if h.proc.poll() is not None:
                continue
            if h.state == "actor" and h.actor_id is not None:
                actors.append({"actor_id": h.actor_id,
                               "address": h.address})
            elif h.state == "leased":
                leased += 1
        return {"actors": actors, "leases": leased,
                "workers": len(self.workers),
                "incarnation": self.incarnation}

    def _fence_self(self, granted_incarnation: int, reason: str):
        """The GCS declared this node dead and failed its actors over;
        everything running here is a stale gang.  Kill ALL workers (an
        op from a fenced incarnation must never double-apply against the
        failed-over replacements), drop bundle reservations, and adopt
        the granted incarnation so the follow-up register is accepted."""
        victims = [h for h in list(self.workers.values())
                   if h.proc.poll() is None]
        logger.warning(
            "fencing node %s: %s (incarnation %d -> %d, killing %d "
            "stale workers)", self.node_id.hex()[:8], reason,
            self.incarnation, granted_incarnation, len(victims))
        events.record("proc", "node_fenced", node=self.node_id.hex()[:8],
                      incarnation=granted_incarnation,
                      stale_workers=len(victims), reason=reason)
        for h in victims:
            self._kill_worker(h)
        self.workers.clear()
        self.bundles.clear()
        self.resources_available = dict(self.resources_total)
        self.incarnation = int(granted_incarnation)

    async def _register_with_gcs(self, timeout: float = 10):
        """Register (or re-register) with the anti-entropy snapshot,
        honoring a fencing verdict: on "fenced" the node kills its stale
        gang, adopts the granted incarnation, and registers again as the
        fresh incarnation.  Stale actors the GCS reports back (workers
        whose incarnation lost ownership while we were partitioned) are
        reaped here."""
        req = {"info": self.node_info(), "snapshot": self._state_snapshot()}
        reply = await self.gcs.call("Gcs", "register_node", req,
                                    timeout=timeout)
        if isinstance(reply, dict) and reply.get("fenced"):
            self._fence_self(
                int(reply.get("incarnation", self.incarnation + 1)),
                "GCS refused registration: node was declared dead")
            reply = await self.gcs.call(
                "Gcs", "register_node",
                {"info": self.node_info(),
                 "snapshot": self._state_snapshot()},
                timeout=timeout)
        stale = (reply.get("stale_actors") or []) \
            if isinstance(reply, dict) else []
        if stale:
            stale_set = set(stale)
            for h in list(self.workers.values()):
                if h.actor_id is not None and h.actor_id in stale_set:
                    logger.warning(
                        "reaping stale actor worker pid %d: its actor "
                        "was failed over while this node was away",
                        h.proc.pid)
                    events.record("proc", "stale_actor_reaped",
                                  node=self.node_id.hex()[:8],
                                  pid=h.proc.pid)
                    self._kill_worker(h)
        return reply

    async def _heartbeat_loop(self):
        from ray_tpu import protocol
        from ray_tpu._private.fault_injection import get_chaos
        last_ok = time.monotonic()
        while not self._shutdown.is_set():
            chaos = get_chaos()
            if chaos is not None and chaos.kill_hostd(self.is_head):
                # Injected node failure: die like a preempted host — no
                # cleanup, no dereg.  The GCS health loop declares the
                # node dead after node_death_timeout_s and fails over its
                # actors; peers learn through their node-watch loops.
                logger.warning("chaos: killing hostd %s",
                               self.node_id.hex()[:8])
                events.record("proc", "chaos_kill",
                              node=self.node_id.hex()[:8])
                events.dump_crash("chaos_kill_hostd")
                os._exit(1)
            if (chaos is not None and not self.preempting
                    and chaos.preempt_hostd(self.is_head)):
                # Injected maintenance event: a preemption NOTICE with a
                # grace window, not an instant kill.  Unlike kill_hostd
                # this may target the head — it degrades to losing only
                # its workers so the colocated GCS survives.
                logger.warning("chaos: preemption notice on hostd %s",
                               self.node_id.hex()[:8])
                asyncio.ensure_future(self.notify_preemption(
                    {"grace_s": _cfg().chaos_preempt_grace_s}))
            try:
                hb = protocol.pb.HeartbeatRequest(
                    node_id=self.node_id.binary())
                for k, v in self.resources_available.items():
                    hb.available.amounts[k] = v
                # outage_retry=False: the heartbeat MEASURES GCS liveness
                # (the silence window below keys on it), so it must fail
                # fast per tick instead of riding the outage out inside
                # the client.
                reply = await self.gcs.call("Gcs", "heartbeat", hb,
                                            timeout=5, outage_retry=False)
                last_ok = time.monotonic()
                if reply.shutdown:
                    self._shutdown.set()
                if reply.reregister:
                    await self._register_with_gcs()
            except Exception:
                # Slow is not dead: a saturated single-core GCS (actor
                # storm, bulk submissions) can stall past any single RPC
                # timeout; a hostd suicide then cascades into hundreds of
                # "connection refused" failures.  Exit only after a
                # sustained silent window — real GCS death also trips the
                # driver/launcher watchdogs.  With a supervised GCS the
                # window never expires the node: the head is coming back
                # at the same address, and a suicide here would turn a
                # restartable head outage into whole-cluster loss.
                silent = time.monotonic() - last_ok
                if silent > float(_cfg().gcs_silent_window_s):
                    if _cfg().gcs_supervise:
                        logger.warning(
                            "GCS unreachable for %.0fs; supervised head — "
                            "riding the outage out", silent)
                        last_ok = time.monotonic()  # re-arm the window
                    else:
                        logger.error(
                            "GCS unreachable for %.0fs; hostd exiting",
                            silent)
                        self._shutdown.set()
            await asyncio.sleep(gcs_mod.HEARTBEAT_INTERVAL_S)

    async def _node_watch_loop(self):
        """Propagate GCS-detected node death to this node's workers
        (reference: raylet subscribes to GCS NodeRemoved and notifies its
        core workers).

        The heartbeat reply is a compiled proto with no room for
        membership deltas, so the daemon polls the GCS node table — the
        cluster version makes the no-change iteration one cheap RPC —
        and, when a peer transitions alive->dead, invalidates the peer's
        pooled channel and pushes a NodeDead notification to every live
        local worker.  Owners there drop the dead node from object
        location sets and purge its worker leases
        (core_worker._rpc_node_dead), reconnecting lease demand to the
        surviving nodes."""
        known_alive: set | None = None
        version = None
        while not self._shutdown.is_set():
            try:
                reply = await self.gcs.call("Gcs", "get_nodes", {},
                                            timeout=5)
            except Exception as e:
                # Not silent: the outage is already metered by the
                # GcsClient (gcs/unreachable + gcs_unreachable_seconds);
                # this marks the watch loop itself as degraded so `cli
                # events` shows WHICH consumer was blind, then keeps
                # polling — membership deltas resume on reconnect.
                events.record("gcs", "unreachable", loop="node_watch",
                              error=str(e)[:120])
                await asyncio.sleep(gcs_mod.HEARTBEAT_INTERVAL_S)
                continue
            boot = reply.get("boot_id")
            if boot is not None and boot != self._gcs_boot_id:
                if self._gcs_boot_id is not None:
                    # The head restarted underneath us.  Its restored
                    # tables list this node alive, so no heartbeat will
                    # nudge a reregister — push the anti-entropy snapshot
                    # proactively so GCS state converges to ground truth.
                    logger.warning("GCS restarted (boot %s); "
                                   "re-registering with snapshot", boot)
                    try:
                        await self._register_with_gcs()
                    except Exception:
                        pass  # the resync-pending heartbeat nudge remains
                self._gcs_boot_id = boot
            if reply.get("version") != version:
                version = reply.get("version")
                nodes = reply["nodes"]
                alive = {n.node_id.hex() for n in nodes if n.alive}
                if known_alive is not None:
                    addr_of = {n.node_id.hex(): n.address for n in nodes}
                    for nid in known_alive - alive:
                        if nid == self.node_id.hex():
                            continue
                        addr = addr_of.get(nid, "")
                        logger.warning("peer node %s (%s) declared dead",
                                       nid[:8], addr)
                        if addr:
                            self.pool.invalidate(addr)
                        await self._broadcast_node_dead(nid, addr)
                known_alive = alive
            await asyncio.sleep(gcs_mod.HEARTBEAT_INTERVAL_S)

    async def _broadcast_node_dead(self, nid_hex: str, addr: str):
        async def _notify(handle):
            try:
                await self.pool.get(handle.address).call(
                    "CoreWorker", "NodeDead",
                    {"node_id": nid_hex, "address": addr}, timeout=2)
            except Exception:
                pass  # worker may be mid-exit; its own RPCs will fail over

        targets = [h for h in list(self.workers.values())
                   if h.address and h.proc.poll() is None]
        if targets:
            await asyncio.gather(*[_notify(h) for h in targets])

    async def _reaper_loop(self):
        """Detect dead/idle-expired workers; report dead actor workers."""
        while not self._shutdown.is_set():
            now = time.monotonic()
            z = self._zygote   # snapshot: _zygote_close can race the await
            if z is not None:
                # Drain reap reports (authoritative exit codes for forked
                # workers) off-loop; the pipe round trip is ~1ms but must
                # not stall RPC serving under load.
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, z.poll_exits, self._zygote_exits)
                except Exception:
                    # Close ONLY if the zygote process is actually dead:
                    # terminating it reparents every forked worker, whose
                    # ppid watch then kills them — one transient pipe
                    # error must never take down the node's workers.
                    if z.proc.poll() is not None:
                        logger.warning("zygote died; cold spawns only")
                        if self._zygote is z:
                            self._zygote_close()
                    else:
                        logger.warning("zygote reap poll failed (kept)")
            for handle in list(self.workers.values()):
                if handle.proc.poll() is not None:
                    # Final log read FIRST: a crashing worker's traceback
                    # is exactly what must reach the driver.
                    ls, ud = self._collect_worker_log_lines(handle,
                                                            final=True)
                    await self._publish_log_lines(ls, ud)
                    self.workers.pop(handle.proc.pid, None)
                    self._release_lease(handle)
                    if handle.state == "actor" and handle.actor_id is not None:
                        reason = (f"worker exited deliberately "
                                  f"({handle.exit_reason})"
                                  if handle.exit_reason else
                                  f"worker exited "
                                  f"({handle.proc.returncode})")
                        try:
                            await self.gcs.call(
                                "Gcs", "report_actor_death",
                                {"actor_id": handle.actor_id,
                                 "address": handle.address,
                                 "reason": reason},
                                timeout=2)
                        except Exception:
                            pass
                elif (handle.state == "idle"
                      and now - handle.idle_since > _cfg().worker_idle_ttl_s):
                    self._kill_worker(handle)
            self._prewarm_tick()
            await asyncio.sleep(0.2)

    def _note_lease_demand(self, job_id: int, runtime_env, tpu: bool,
                           count: int = 1) -> None:
        from ray_tpu._private import runtime_env as renv
        key = (job_id, renv.env_hash(runtime_env), tpu)
        t = time.monotonic()
        for _ in range(min(count, 64)):
            self._lease_demand.append((t, key, runtime_env))

    def _prewarm_tick(self, window_s: float = 5.0):
        """Keep idle workers forked ahead of demand: recent lease traffic
        for a (job, env, non-TPU) pool seeds up to zygote_spawn_parallelism
        spare workers per tick, so the next storm wave claims an idle fork
        instead of paying a cold spawn inside its lease RPC.  Only while
        the zygote is serving (forks are ~1-2ms; pre-warming cold Popens
        would fight the startup throttle it exists to protect)."""
        if (self._zygote is None or self.preempting
                or not _cfg().worker_prewarm or not self._lease_demand):
            return
        # Pre-warm only fills SPARE startup capacity.  During a storm the
        # lease path keeps the throttle saturated on its own; unthrottled
        # extra forks would steal CPU from boots already in flight (which
        # is strictly worse than doing nothing — measured 23/s -> 8/s on
        # a 1-core actor storm before this guard existed).
        starting = sum(1 for w in self.workers.values()
                       if w.state == "starting") + self._spawning
        headroom = self.max_startup_concurrency - starting
        if headroom <= 0:
            return
        horizon = time.monotonic() - window_s
        while self._lease_demand and self._lease_demand[0][0] < horizon:
            self._lease_demand.popleft()
        if not self._lease_demand:
            return
        demand: dict = {}
        envs: dict = {}
        for _, key, runtime_env in self._lease_demand:
            demand[key] = demand.get(key, 0) + 1
            envs[key] = runtime_env
        live = sum(1 for w in self.workers.values()
                   if w.proc.returncode is None)
        budget = min(_cfg().zygote_spawn_parallelism, headroom,
                     self.max_workers - live)
        for (job_id, env_hash, tpu), seen in sorted(
                demand.items(), key=lambda kv: -kv[1]):
            if budget <= 0:
                break
            if tpu:
                continue   # TPU workers never fork; no cheap pre-warm
            # Supply = every live matching worker, whatever its state:
            # leases counted in `seen` were served by workers that are
            # now leased/actor — counting only idle+starting here would
            # re-buy satisfied demand every tick.
            have = sum(1 for w in self.workers.values()
                       if w.job_id == job_id and w.env_hash == env_hash
                       and not w.tpu and w.proc.returncode is None)
            want = min(budget, seen - have)
            for _ in range(max(0, want)):
                budget -= 1
                asyncio.ensure_future(
                    self._spawn_worker(job_id, envs[(job_id, env_hash,
                                                     tpu)], False))

    async def start(self, port: int = 0) -> int:
        self.server.register("NodeManager", "WorkerReady", self.worker_ready)
        self.server.register("NodeManager", "LeaseWorker", self.lease_worker)
        self.server.register("NodeManager", "ReturnWorker", self.return_worker)
        self.server.register("NodeManager", "LeaseWorkerForActor",
                             self.lease_worker_for_actor)
        self.server.register("NodeManager", "PrepareBundle",
                             self.prepare_bundle)
        self.server.register("NodeManager", "CommitBundle",
                             self.commit_bundle)
        self.server.register("NodeManager", "CancelBundle",
                             self.cancel_bundle)
        self.server.register("NodeManager", "PullObject", self.pull_object)
        self.server.register("NodeManager", "PullObjectMeta",
                             self.pull_object_meta)
        self.server.register("NodeManager", "PullObjectChunk",
                             self.pull_object_chunk)
        self.server.register("NodeManager", "PushObject", self.push_object)
        self.server.register("NodeManager", "FreeObject", self.free_object)
        self.server.register("NodeManager", "FreeObjects", self.free_objects)
        self.server.register("NodeManager", "StoreStats", self.store_stats)
        self.server.register("NodeManager", "SpillObjects",
                             self.spill_objects)
        self.server.register("NodeManager", "ListWorkers", self.list_workers)
        self.server.register("NodeManager", "StackTraces", self.stack_traces)
        self.server.register("NodeManager", "CollectStacks",
                             self.collect_stacks)
        self.server.register("NodeManager", "NotifyPreemption",
                             self.notify_preemption)
        self.server.register("NodeManager", "WorkerExiting",
                             self.worker_exiting)
        self.server.register("NodeManager", "Metrics", self.get_metrics)
        self.server.register("NodeManager", "CollectEvents",
                             self.collect_events)
        self.server.register("NodeManager", "ShutdownNode", self.shutdown_node)
        port = await self.server.start(port)
        # Native bulk-data plane: serves this store's sealed objects over
        # raw TCP (objtransfer.cc); pullers learn the port from the
        # PullObjectMeta probe.
        try:
            from ray_tpu._private.object_transfer import TransferServer
            self.transfer_server = TransferServer(self.store_path)
        except Exception as e:
            logger.warning("native transfer plane unavailable: %s", e)
            self.transfer_server = None
        await self._register_with_gcs(timeout=10)
        if _cfg().worker_zygote:
            self._prestart_zygote()  # off-loop; cold imports never block
        self._tasks = [asyncio.ensure_future(self._heartbeat_loop()),
                       asyncio.ensure_future(self._reaper_loop()),
                       asyncio.ensure_future(self._node_watch_loop())]
        if self.spill_enabled:
            self.store.set_eviction(False)
            self._tasks.append(asyncio.ensure_future(self._spill_loop()))
        if _cfg().memory_monitor_enabled:
            self._tasks.append(
                asyncio.ensure_future(self._memory_monitor_loop()))
        self._tasks.append(asyncio.ensure_future(self._log_tail_loop()))
        self._start_telemetry()
        return port

    def _start_telemetry(self):
        """Pull endpoints (/metrics /events /healthz) for external
        scrapers.  Handlers run on the HTTP thread pool and hop onto the
        daemon loop for the node-level merges; rides the flight-recorder
        switch (RAY_TPU_EVENTS=0 -> no server)."""
        from ray_tpu.util import telemetry
        loop = asyncio.get_running_loop()

        def metrics_fn():
            from ray_tpu.util import metrics as mt
            reply = asyncio.run_coroutine_threadsafe(
                self.get_metrics({}), loop).result(timeout=10)
            return mt.prometheus_text(
                reply.get("metrics", {}),
                {"component": "hostd", "node_id": self.node_id.hex()[:12]})

        def events_fn(plane, kind, trace_id, since):
            reply = asyncio.run_coroutine_threadsafe(
                self.collect_events({"since": since}), loop).result(
                    timeout=10)
            return [e for e in reply.get("events", [])
                    if (plane is None or e.get("plane") == plane)
                    and (kind is None or e.get("kind") == kind)
                    and (trace_id is None or e.get("trace_id") == trace_id)]

        def healthz_fn():
            return {"node_id": self.node_id.hex(),
                    "workers": len(self.workers)}

        self.telemetry = telemetry.start_server(
            metrics_fn=metrics_fn, events_fn=events_fn,
            component="hostd", healthz_fn=healthz_fn)

    def install_signal_handlers(self):
        import signal
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self._shutdown.set)
            except (NotImplementedError, RuntimeError):
                pass

    async def run_until_shutdown(self):
        await self._shutdown.wait()
        # Black box + profile flush before the teardown starts killing
        # things: this daemon's ring records the node's last decisions.
        events.record("proc", "hostd_shutdown",
                      node=self.node_id.hex()[:8])
        events.dump_crash("hostd_shutdown")
        from ray_tpu._private.profiling import stop_periodic_profiles
        stop_periodic_profiles()
        if getattr(self, "telemetry", None) is not None:
            self.telemetry.stop()
            self.telemetry = None
        for t in self._tasks:
            t.cancel()
        # Teardown escalation: SIGTERM everyone, give the pool one shared
        # grace window to drain (workers' own SIGTERM handlers finish the
        # in-flight task), then SIGKILL any survivor — shutdown can never
        # wedge on a worker whose native code ignores SIGTERM.
        victims = list(self.workers.values())
        for handle in victims:
            self._kill_worker(handle)
        self._zygote_close()
        deadline = time.monotonic() + max(3.0, _cfg().worker_sigterm_grace_s)
        for handle in victims:
            try:
                handle.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                handle.proc.kill()
        await self.server.stop()
        await self.pool.close_all()
        await self.gcs.close()
        if getattr(self, "transfer_server", None) is not None:
            # close() blocks in native code (join + drain, up to ~5s) —
            # keep it off the event loop.
            await asyncio.get_running_loop().run_in_executor(
                None, self.transfer_server.close)
        self.store.close()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--ready-file", default="")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--resources", default="")  # "k=v,k=v"
    parser.add_argument("--store-capacity", type=int, default=256 << 20)
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    args = parser.parse_args()
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOGLEVEL", "INFO"), format="%(asctime)s.%(msecs)03d %(message)s", datefmt="%H:%M:%S")

    resources = detect_resources()
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    if args.num_tpus is not None:
        if args.num_tpus > 0:
            resources["TPU"] = args.num_tpus
        else:
            resources.pop("TPU", None)
    for kv in filter(None, args.resources.split(",")):
        k, v = kv.split("=")
        resources[k] = float(v)

    from ray_tpu._private.profiling import start_periodic_profile
    start_periodic_profile("RAY_TPU_PROFILE_HOSTD", "hostd")

    async def run():
        daemon = NodeDaemon(args.gcs, resources, args.store_capacity,
                            is_head=args.head, host=args.host,
                            session_dir=args.session_dir)
        port = await daemon.start(args.port)
        daemon.install_signal_handlers()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{port}\n{daemon.node_id.hex()}\n{daemon.store_path}")
            os.replace(tmp, args.ready_file)
        logger.info("hostd %s on port %d resources=%s",
                    daemon.node_id.hex()[:8], port, resources)
        await daemon.run_until_shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
