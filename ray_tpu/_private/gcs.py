"""GCS — the cluster control plane.

Reference parity: src/ray/gcs/gcs_server/ — GcsKvManager, GcsNodeManager,
GcsHealthCheckManager, GcsActorManager (+GcsActorScheduler two-phase
register/create, gcs_actor_manager.h:249), GcsResourceManager, GcsJobManager.
One asyncio process; state is in-memory (the reference's default
gcs_storage="memory", ray_config_def.h:382) with a pluggable table layer so a
persistent backend can slot in later.

Scheduling policy: the cluster-wide resource view lives here (fed by hostd
heartbeats, the reference's RaySyncer gossip), and `pick_node` implements the
hybrid/spread/affinity policies of src/ray/raylet/scheduling/policy/.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import time

from ray_tpu._private.ids import ActorID, NodeID
from ray_tpu._private.protocol import ActorInfo, NodeInfo
from ray_tpu._private.rpc import ClientPool, RpcServer
from ray_tpu._private import scheduler as sched

logger = logging.getLogger("ray_tpu.gcs")

HEARTBEAT_INTERVAL_S = 0.5
NODE_DEATH_TIMEOUT_S = 5.0


class KvManager:
    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}

    async def kv_put(self, req):
        ns = self._data.setdefault(req.get("ns", ""), {})
        existed = req["key"] in ns
        if req.get("overwrite", True) or not existed:
            ns[req["key"]] = req["value"]
        return {"existed": existed}

    async def kv_get(self, req):
        return {"value": self._data.get(req.get("ns", ""), {}).get(req["key"])}

    async def kv_del(self, req):
        ns = self._data.get(req.get("ns", ""), {})
        return {"deleted": ns.pop(req["key"], None) is not None}

    async def kv_exists(self, req):
        return {"exists": req["key"] in self._data.get(req.get("ns", ""), {})}

    async def kv_keys(self, req):
        ns = self._data.get(req.get("ns", ""), {})
        prefix = req.get("prefix", "")
        return {"keys": [k for k in ns if k.startswith(prefix)]}


class GcsServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.kv = KvManager()
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.node_heartbeat: dict[NodeID, float] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.placement_groups = {}  # filled by PG manager (milestone: PGs)
        self.pool = ClientPool()
        self.server = RpcServer(host)
        self.next_job = 0
        self._job_lock = asyncio.Lock()
        self._shutdown = asyncio.Event()
        self._cluster_version = 0  # bumped on node/actor table changes

    # ---------------- node manager ----------------

    async def register_node(self, req):
        info: NodeInfo = req["info"]
        self.nodes[info.node_id] = info
        self.node_heartbeat[info.node_id] = time.monotonic()
        self._cluster_version += 1
        logger.info("node %s registered at %s (%s)", info.node_id.hex()[:8],
                    info.address, info.resources_total)
        return {"ok": True}

    async def heartbeat(self, req):
        nid = req["node_id"]
        info = self.nodes.get(nid)
        if info is None or not info.alive:
            return {"ok": False, "reregister": True}
        self.node_heartbeat[nid] = time.monotonic()
        info.resources_available = req["available"]
        return {"ok": True, "shutdown": self._shutdown.is_set()}

    async def get_nodes(self, req):
        return {"nodes": list(self.nodes.values()),
                "version": self._cluster_version}

    async def drain_node(self, req):
        await self._mark_node_dead(req["node_id"], "drained")
        return {"ok": True}

    async def _mark_node_dead(self, nid: NodeID, reason: str):
        info = self.nodes.get(nid)
        if info is None or not info.alive:
            return
        info.alive = False
        self._cluster_version += 1
        logger.warning("node %s dead: %s", nid.hex()[:8], reason)
        # Fail over actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == nid and actor.state in ("ALIVE", "PENDING"):
                await self._on_actor_interrupted(actor, f"node died: {reason}")

    async def _health_loop(self):
        while not self._shutdown.is_set():
            now = time.monotonic()
            for nid, last in list(self.node_heartbeat.items()):
                info = self.nodes.get(nid)
                if info is not None and info.alive and not info.is_head \
                        and now - last > NODE_DEATH_TIMEOUT_S:
                    await self._mark_node_dead(nid, "heartbeat timeout")
            await asyncio.sleep(HEARTBEAT_INTERVAL_S)

    # ---------------- job manager ----------------

    async def next_job_id(self, req):
        async with self._job_lock:
            self.next_job += 1
            return {"job_id": self.next_job}

    # ---------------- actor manager ----------------
    # Two-phase as in the reference (gcs_actor_manager.h:249): RegisterActor
    # persists the record, CreateActor drives scheduling.  We fuse the
    # scheduling trigger into register for simplicity but keep the externally
    # visible states PENDING -> ALIVE (-> RESTARTING) -> DEAD.

    async def register_actor(self, req):
        info: ActorInfo = req["info"]
        if info.name:
            key = (info.namespace, info.name)
            existing_id = self.named_actors.get(key)
            if existing_id is not None:
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != "DEAD":
                    if req.get("get_if_exists"):
                        return {"existing": existing}
                    raise ValueError(
                        f"actor name {info.name!r} already taken in "
                        f"namespace {info.namespace!r}")
            self.named_actors[key] = info.actor_id
        self.actors[info.actor_id] = info
        asyncio.ensure_future(self._schedule_actor(info))
        return {"existing": None}

    async def _schedule_actor(self, info: ActorInfo):
        """Lease a dedicated worker on some node and run the creation task."""
        demand = info.resources.to_dict()
        # Pick with >=1 CPU so default actors land on nodes with headroom,
        # but reserve only the declared demand (1-for-scheduling /
        # 0-for-running, as in the reference).
        pick_demand = demand or {"CPU": 1.0}
        tried: set[NodeID] = set()
        for _ in range(100):
            if info.state == "DEAD":
                return
            node = sched.pick_node(self._alive_nodes(), pick_demand,
                                   strategy="DEFAULT", exclude=tried)
            if node is None:
                await asyncio.sleep(0.2)  # wait for capacity / new nodes
                tried.clear()
                continue
            job_int = int.from_bytes(
                info.creation_spec.job_id.binary(), "little") \
                if info.creation_spec is not None else 0
            try:
                lease = await self.pool.get(node.address).call(
                    "NodeManager", "LeaseWorkerForActor",
                    {"actor_id": info.actor_id, "resources": demand,
                     "job_id": job_int},
                    timeout=30)
            except Exception as e:
                logger.info("lease on %s failed: %s", node.address, e)
                tried.add(node.node_id)
                continue
            if not lease.get("granted"):
                tried.add(node.node_id)
                continue
            worker_addr = lease["worker_address"]
            try:
                reply = await self.pool.get(worker_addr).call(
                    "CoreWorker", "CreateActor",
                    {"spec": info.creation_spec, "actor_id": info.actor_id},
                    timeout=120)
            except Exception as e:
                logger.warning("actor %s creation push failed: %s",
                               info.actor_id.hex()[:8], e)
                tried.add(node.node_id)
                continue
            if info.state == "DEAD":
                # Killed while we were scheduling it: don't resurrect; tear
                # down the worker we just created it on.
                try:
                    await self.pool.get(worker_addr).call(
                        "CoreWorker", "KillActor",
                        {"actor_id": info.actor_id, "no_restart": True},
                        timeout=5)
                except Exception:
                    pass
                return
            if reply.get("error") is not None:
                info.state = "DEAD"
                info.death_cause = f"creation failed: {reply['error']}"
                info.version += 1
                self._cluster_version += 1
                return
            info.state = "ALIVE"
            info.address = worker_addr
            info.node_id = node.node_id
            info.version += 1
            self._cluster_version += 1
            logger.info("actor %s alive at %s", info.actor_id.hex()[:8],
                        worker_addr)
            return
        info.state = "DEAD"
        info.death_cause = "scheduling failed after 100 attempts"
        info.version += 1

    async def _on_actor_interrupted(self, actor: ActorInfo, reason: str):
        if actor.num_restarts < actor.max_restarts or actor.max_restarts == -1:
            actor.num_restarts += 1
            actor.state = "RESTARTING"
            actor.address = ""
            actor.version += 1
            self._cluster_version += 1
            logger.info("restarting actor %s (%d/%s): %s",
                        actor.actor_id.hex()[:8], actor.num_restarts,
                        actor.max_restarts, reason)
            asyncio.ensure_future(self._schedule_actor(actor))
        else:
            actor.state = "DEAD"
            actor.death_cause = reason
            actor.address = ""
            actor.version += 1
            self._cluster_version += 1

    async def report_actor_death(self, req):
        actor = self.actors.get(req["actor_id"])
        if actor is not None and actor.state in ("ALIVE", "PENDING"):
            if req.get("intentional"):
                actor.state = "DEAD"
                actor.death_cause = req.get("reason", "killed")
                actor.address = ""
                actor.version += 1
                self._cluster_version += 1
            else:
                await self._on_actor_interrupted(actor, req.get("reason", "?"))
        return {"ok": True}

    async def get_actor_info(self, req):
        actor = self.actors.get(req["actor_id"])
        # Long-poll: while the actor is pending/restarting, hold the request
        # briefly so callers don't spin (reference: pubsub long-poll).
        deadline = time.monotonic() + req.get("wait_s", 0)
        while actor is not None and actor.state in ("PENDING", "RESTARTING") \
                and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        return {"info": actor}

    async def get_named_actor(self, req):
        aid = self.named_actors.get((req.get("namespace", "default"), req["name"]))
        return {"info": self.actors.get(aid) if aid else None}

    async def list_actors(self, req):
        return {"actors": list(self.actors.values())}

    async def kill_actor(self, req):
        actor = self.actors.get(req["actor_id"])
        if actor is None:
            return {"ok": False}
        no_restart = req.get("no_restart", True)
        address = actor.address
        if no_restart:
            actor.state = "DEAD"
            actor.death_cause = "ray_tpu.kill"
            actor.address = ""
            actor.version += 1
            self._cluster_version += 1
        else:
            # Kill the process but honor max_restarts (reference:
            # ray.kill(no_restart=False) semantics).
            await self._on_actor_interrupted(actor, "ray_tpu.kill(no_restart=False)")
        if address:
            try:
                await self.pool.get(address).call(
                    "CoreWorker", "KillActor",
                    {"actor_id": req["actor_id"], "no_restart": no_restart},
                    timeout=5)
            except Exception:
                pass
        return {"ok": True}

    # ---------------- scheduling service ----------------

    async def pick_node(self, req):
        node = sched.pick_node(
            self._alive_nodes(), req["resources"],
            strategy=req.get("strategy", "DEFAULT"),
            exclude=set(req.get("exclude") or ()),
            affinity=req.get("node_affinity"),
            affinity_soft=req.get("node_affinity_soft", True),
        )
        return {"node": node}

    def _alive_nodes(self) -> list[NodeInfo]:
        return [n for n in self.nodes.values() if n.alive]

    # ---------------- cluster lifecycle ----------------

    async def cluster_resources(self, req):
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        for n in self._alive_nodes():
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def shutdown_cluster(self, req):
        self._shutdown.set()
        return {"ok": True}

    async def ping(self, req):
        return {"ok": True, "version": self._cluster_version}

    # ---------------- lifecycle ----------------

    async def start(self, port: int = 0) -> int:
        self.server.register_service("Kv", self.kv)
        self.server.register_service("Gcs", self)
        port = await self.server.start(port)
        self._health_task = asyncio.ensure_future(self._health_loop())
        return port

    async def run_until_shutdown(self):
        await self._shutdown.wait()
        await asyncio.sleep(2 * HEARTBEAT_INTERVAL_S)  # let hostds see it
        await self.server.stop()
        await self.pool.close_all()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--ready-file", default="")
    args = parser.parse_args()
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOGLEVEL", "INFO"))

    async def run():
        gcs = GcsServer(args.host)
        port = await gcs.start(args.port)
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.ready_file)
        logger.info("GCS listening on %s:%d", args.host, port)
        await gcs.run_until_shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
