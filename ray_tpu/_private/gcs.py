"""GCS — the cluster control plane.

Reference parity: src/ray/gcs/gcs_server/ — GcsKvManager, GcsNodeManager,
GcsHealthCheckManager, GcsActorManager (+GcsActorScheduler two-phase
register/create, gcs_actor_manager.h:249), GcsResourceManager, GcsJobManager.
One asyncio process; state is in-memory (the reference's default
gcs_storage="memory", ray_config_def.h:382) with a pluggable table layer so a
persistent backend can slot in later.

Scheduling policy: the cluster-wide resource view lives here (fed by hostd
heartbeats, the reference's RaySyncer gossip), and `pick_node` implements the
hybrid/spread/affinity policies of src/ray/raylet/scheduling/policy/.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import time

from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu._private.protocol import ActorInfo, NodeInfo, PlacementGroupInfo
from ray_tpu._private.rpc import ClientPool, RpcServer
from ray_tpu._private import scheduler as sched

logger = logging.getLogger("ray_tpu.gcs")

def _cfg():
    from ray_tpu._private.config import GLOBAL_CONFIG
    return GLOBAL_CONFIG


_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "actors_created": mt.Counter(
                "actors_created", "actors scheduled successfully"),
            "actor_restarts": mt.Counter(
                "actor_restarts", "actor failover restarts"),
            "placement_groups_created": mt.Counter(
                "placement_groups_created", "placement groups scheduled"),
            "nodes_alive": mt.Gauge("nodes_alive", "alive nodes"),
            "gcs_flush_rows": mt.Counter(
                "gcs_flush_rows", "rows written by GCS persistence flushes"),
            "gcs_flush_seconds": mt.Counter(
                "gcs_flush_seconds", "seconds spent in GCS flush commits"),
        }
    return _M


HEARTBEAT_INTERVAL_S = _cfg().heartbeat_interval_s
NODE_DEATH_TIMEOUT_S = _cfg().node_death_timeout_s


class KvManager:
    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}
        self.on_change = None  # set by GcsServer for persistence

    async def kv_put(self, req):
        """Typed (pb.KvPutRequest) or legacy dict (reference: the KV rows
        of gcs_service.proto InternalKVPut)."""
        from ray_tpu import protocol
        typed = protocol.is_message(req)
        if typed:
            req = {"ns": req.ns, "key": req.key, "value": req.value,
                   "overwrite": req.overwrite}
        ns = self._data.setdefault(req.get("ns", ""), {})
        existed = req["key"] in ns
        if req.get("overwrite", True) or not existed:
            ns[req["key"]] = req["value"]
            if self.on_change is not None:
                self.on_change(req.get("ns", ""), req["key"])
        if typed:
            return protocol.pb.KvPutReply(existed=existed)
        return {"existed": existed}

    async def kv_get(self, req):
        from ray_tpu import protocol
        if protocol.is_message(req):
            v = self._data.get(req.ns, {}).get(req.key)
            return protocol.pb.KvGetReply(found=v is not None,
                                          value=v or b"")
        return {"value": self._data.get(req.get("ns", ""), {}).get(req["key"])}

    async def kv_del(self, req):
        from ray_tpu import protocol
        typed = protocol.is_message(req)
        if typed:
            req = {"ns": req.ns, "key": req.key}
        ns = self._data.get(req.get("ns", ""), {})
        deleted = ns.pop(req["key"], None) is not None
        if deleted and self.on_change is not None:
            # Without this, a deleted key would resurrect on restore.
            self.on_change(req.get("ns", ""), req["key"])
        if typed:
            return protocol.pb.KvDelReply(deleted=deleted)
        return {"deleted": deleted}

    async def kv_exists(self, req):
        return {"exists": req["key"] in self._data.get(req.get("ns", ""), {})}

    async def kv_keys(self, req):
        ns = self._data.get(req.get("ns", ""), {})
        prefix = req.get("prefix", "")
        return {"keys": [k for k in ns if k.startswith(prefix)]}


class GcsTableStorage:
    """Pluggable control-plane persistence (reference:
    gcs/store_client/ — in_memory_store_client.h vs redis_store_client.h,
    selected by gcs_storage, ray_config_def.h:382).  The sqlite backend
    stores one row per record, so a mutation costs O(changed records) —
    the redis store client's role — not a whole-state snapshot; node
    membership IS persisted (the reference keeps the node table in the
    GCS store and reconciles against re-registration after restart)."""

    def __init__(self, path: str | None):
        self.path = path  # None = memory-only
        self._db = None
        self.write_ops = 0  # rows written, for O(delta) assertions

    def _conn(self):
        if self._db is None and self.path:
            import sqlite3
            db = sqlite3.connect(self.path, check_same_thread=False)
            try:
                db.execute("PRAGMA journal_mode=WAL")
                db.execute("PRAGMA synchronous=NORMAL")
                db.execute(
                    "CREATE TABLE IF NOT EXISTS t "
                    "(tab TEXT, k BLOB, v BLOB, PRIMARY KEY (tab, k))")
                db.commit()
            except sqlite3.DatabaseError:
                # Unreadable / pre-sqlite persist file: rotate it away and
                # start fresh rather than wedging the control plane.
                db.close()
                try:
                    os.replace(self.path, self.path + ".corrupt")
                except OSError:
                    pass
                db = sqlite3.connect(self.path, check_same_thread=False)
                db.execute(
                    "CREATE TABLE IF NOT EXISTS t "
                    "(tab TEXT, k BLOB, v BLOB, PRIMARY KEY (tab, k))")
                db.commit()
            self._db = db
        return self._db

    def write_rows(self, puts: list, dels: list) -> None:
        """One transaction: upsert `puts` [(tab, key, value)] and remove
        `dels` [(tab, key)]."""
        db = self._conn()
        if db is None:
            return
        with db:
            if puts:
                db.executemany(
                    "INSERT INTO t (tab, k, v) VALUES (?, ?, ?) "
                    "ON CONFLICT(tab, k) DO UPDATE SET v=excluded.v", puts)
            if dels:
                db.executemany("DELETE FROM t WHERE tab=? AND k=?", dels)
            # Scripted mid-flush kill: every row of this flush is staged
            # on the connection but the transaction has NOT committed.
            # Dying here must roll the whole flush back on restore —
            # the crash-atomicity proof for the coalesced-write path.
            from ray_tpu._private.fault_injection import get_chaos
            chaos = get_chaos()
            if chaos is not None and chaos.kill_gcs_flush():
                from ray_tpu.util import events
                events.record("gcs", "chaos_kill_flush",
                              rows=len(puts) + len(dels))
                events.dump_crash("chaos_kill_gcs_flush")
                os._exit(1)
        self.write_ops += len(puts) + len(dels)

    def load_all(self) -> dict | None:
        """{tab: {key_bytes: value_bytes}} or None when empty/memory-only."""
        import sqlite3
        if not self.path or not os.path.exists(self.path):
            return None
        db = self._conn()
        if db is None:
            return None
        try:
            rows = db.execute("SELECT tab, k, v FROM t").fetchall()
        except sqlite3.DatabaseError:
            return None
        out: dict = {}
        for tab, k, v in rows:
            out.setdefault(tab, {})[bytes(k)] = bytes(v)
        return out or None

    def close(self):
        if self._db is not None:
            self._db.close()
            self._db = None


class GcsServer:
    def __init__(self, host: str = "127.0.0.1",
                 storage: GcsTableStorage | None = None):
        self.host = host
        self.storage = storage or GcsTableStorage(
            os.environ.get("RAY_TPU_GCS_PERSIST") or None)
        self._persist_pending = False
        self._dirty: set = set()   # (tab, key) records awaiting a flush
        # Serializes flushes: two concurrent write_rows on the shared
        # sqlite connection could interleave and commit a STALE value of
        # a key dirtied in both windows over the fresh one.
        self._persist_lock = asyncio.Lock()
        self.kv = KvManager()
        self.kv.on_change = lambda ns, key: self._mark_dirty("kv", (ns, key))
        self._task_events: list = []  # ring buffer for the timeline
        self._log_lines: list = []    # (seq, record) worker-log ring
        self._log_seq = 0
        # Generic pub/sub channels (reference: src/ray/pubsub/ long-poll
        # publisher/subscriber): channel -> ring of (seq, message).
        self._channels: dict[str, list] = {}
        self._channel_seq: dict[str, int] = {}
        self._channel_events: dict[str, asyncio.Event] = {}
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.node_heartbeat: dict[NodeID, float] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.placement_groups: dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.pool = ClientPool()
        self._native_sub = None   # lazy framed-TCP pusher (taskrpc.cc)
        self.server = RpcServer(host)
        self.next_job = 0
        self._job_lock = asyncio.Lock()
        self._shutdown = asyncio.Event()
        self._cluster_version = 0  # bumped on node/actor table changes
        # Event-driven waiters: every state change swaps + fires this event
        # so long-polls and scheduler retries wake immediately instead of
        # sleep-polling (reference: pubsub/publisher.h long-poll channels).
        self._change_event = asyncio.Event()
        self._actor_events: dict = {}   # ActorID -> Event (targeted polls)
        self._wake_scheduled = False    # coalesces broadcast wakes per tick
        # Per-boot nonce, carried on every get_nodes reply: a supervised
        # respawn binds the same address, so a changed boot_id is how
        # clients detect "the GCS restarted underneath me" and push their
        # anti-entropy re-register even though the restored node table
        # still lists them alive (no reregister nudge from heartbeats).
        self.boot_id = os.urandom(8).hex()
        # Restored-alive nodes that still owe that re-register; their
        # heartbeats answer reregister=True until the snapshot arrives.
        self._resync_pending: set = set()

    def _bump(self, tab: str | None = None, key=None):
        """Record a state change and wake every waiter.  With (tab, key)
        the changed record is marked dirty for the incremental persist
        flush; without them the change is volatile (resource heartbeats)
        and only wakes waiters.

        The broadcast wake is coalesced to once per loop tick: a batched
        mutation (N actors registered in one RPC burst) fires the parked
        long-polls a single time instead of N times, while targeted
        per-actor wakes stay immediate."""
        self._cluster_version += 1
        if not self._wake_scheduled:
            self._wake_scheduled = True
            try:
                asyncio.get_running_loop().call_soon(self._fire_change)
            except RuntimeError:   # no loop (teardown/test) — fire inline
                self._fire_change()
        if tab == "actors" and key is not None:
            # Targeted wake for per-actor long-polls: during an actor
            # storm, hundreds of get_actor_info polls are parked, and
            # waking ALL of them on EVERY cluster change is an O(n^2)
            # coroutine stampede.
            aev = self._actor_events.pop(key, None)
            if aev is not None:
                aev.set()
        if tab is not None:
            self._dirty.add((tab, key))
            self._schedule_persist()

    def _fire_change(self):
        self._wake_scheduled = False
        ev = self._change_event
        self._change_event = asyncio.Event()
        ev.set()

    def _mark_dirty(self, tab: str, key) -> None:
        self._dirty.add((tab, key))
        self._schedule_persist()

    def _schedule_persist(self):
        if self.storage.path and not self._persist_pending:
            self._persist_pending = True
            asyncio.ensure_future(self._persist_soon())

    # Durable tables: dirty-set tab name -> live dict (record pickled per
    # row; a flush touches only rows dirtied since the last one).
    def _tables(self) -> dict:
        return {
            "actors": self.actors,
            "nodes": self.nodes,
            "named_actors": self.named_actors,
            "placement_groups": self.placement_groups,
            "kv": None,  # nested ns dict, resolved in _persist_soon
        }

    async def _persist_soon(self):
        """Debounced incremental flush: a burst of changes becomes ONE
        transaction writing only the dirtied rows (O(delta), reference
        redis_store_client role) plus a constant meta row."""
        await asyncio.sleep(max(0.0, _cfg().gcs_flush_interval_ms) / 1000.0)
        self._persist_pending = False
        import pickle
        async with self._persist_lock:
            await self._flush_dirty(pickle)

    async def _flush_dirty(self, pickle):
        dirty, self._dirty = self._dirty, set()
        if not dirty:
            return
        tables = self._tables()
        puts, dels = [], []
        # Serialize ON the loop thread (no mutation can interleave — a
        # torn row would mix pre/post-transition state), then hand only
        # opaque rows to the executor for disk IO.
        for tab, key in dirty:
            kb = pickle.dumps(key, protocol=5)
            if tab == "kv":
                ns, k = key
                table = self.kv._data.get(ns, {})
                obj, present = table.get(k), k in table
            else:
                d = tables.get(tab)
                if d is None:
                    continue
                obj, present = d.get(key), key in d
            if present:
                puts.append((tab, kb, pickle.dumps(obj, protocol=5)))
            else:
                dels.append((tab, kb))
        puts.append(("meta", b"next_job",
                     pickle.dumps(self.next_job, protocol=5)))
        puts.append(("meta", b"cluster_version",
                     pickle.dumps(self._cluster_version, protocol=5)))
        from ray_tpu.util import spans
        tok = spans.begin("gcs", "flush",
                          rows=len(puts) + len(dels), dirty=len(dirty))
        t0 = time.monotonic()
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self.storage.write_rows, puts, dels)
            spans.end(tok)
            m = _metrics()
            m["gcs_flush_rows"].inc(len(puts) + len(dels))
            m["gcs_flush_seconds"].inc(time.monotonic() - t0)
        except Exception:
            spans.end(tok, error=True)
            logger.exception("GCS persistence write failed")
            # Re-mark AND reschedule: without the reschedule a transient
            # write failure during a quiescent period would leave durable
            # state unwritten until some unrelated future mutation.
            self._dirty |= dirty
            self._schedule_persist()

    def _restore(self) -> None:
        import pickle
        state = self.storage.load_all()
        if not state:
            return
        unp = pickle.loads
        for kb, vb in state.get("actors", {}).items():
            self.actors[unp(kb)] = unp(vb)
        for kb, vb in state.get("named_actors", {}).items():
            self.named_actors[unp(kb)] = unp(vb)
        for kb, vb in state.get("placement_groups", {}).items():
            self.placement_groups[unp(kb)] = unp(vb)
        now = time.monotonic()
        for kb, vb in state.get("nodes", {}).items():
            info = unp(vb)
            self.nodes[unp(kb)] = info
            if info.alive:
                # Grace stamp: a surviving hostd keeps heartbeating and
                # stays; a gone one times out through the normal sweep.
                self.node_heartbeat[unp(kb)] = now
        for kb, vb in state.get("kv", {}).items():
            ns, k = unp(kb)
            self.kv._data.setdefault(ns, {})[k] = unp(vb)
        meta = state.get("meta", {})
        if b"next_job" in meta:
            self.next_job = max(self.next_job, unp(meta[b"next_job"]))
        if b"cluster_version" in meta:
            self._cluster_version = unp(meta[b"cluster_version"])
        # Restored tables are a *hypothesis* about the cluster, not ground
        # truth: every restored-alive node owes an anti-entropy snapshot
        # before its heartbeats read as healthy again.
        self._resync_pending = {nid for nid, info in self.nodes.items()
                                if info.alive}
        logger.info("restored GCS state: %d actors, %d PGs, %d nodes, "
                    "job=%d", len(self.actors), len(self.placement_groups),
                    len(self.nodes), self.next_job)
        from ray_tpu.util import events
        events.record("gcs", "restored", boot=self.boot_id,
                      actors=len(self.actors),
                      pgs=len(self.placement_groups),
                      nodes=len(self.nodes))
        asyncio.ensure_future(self._reconcile_restored())

    async def _reconcile_restored(self):
        """Post-restart reconciliation (reference: RayletNotifyGCSRestart,
        core_worker.proto:403): ping restored ALIVE actors; unreachable
        ones go through the normal interruption/restart path.  PGs lose
        their bundle placements (nodes re-register fresh) and reschedule."""
        for info in list(self.placement_groups.values()):
            if info.state in ("CREATED", "PENDING", "RESCHEDULING"):
                info.state = "PENDING"
                info.bundle_nodes = [None] * len(info.bundles)
                info.bundle_addresses = [""] * len(info.bundles)
                asyncio.ensure_future(self._schedule_pg(info))
        for actor in list(self.actors.values()):
            if actor.state in ("PENDING", "RESTARTING"):
                # Never failed — resume scheduling without burning a
                # restart from the budget.
                asyncio.ensure_future(self._schedule_actor(actor))
                continue
            if actor.state != "ALIVE":
                continue
            reachable = False
            if actor.address:
                try:
                    await self.pool.get(actor.address).call(
                        "CoreWorker", "Ping", {}, timeout=5)
                    reachable = True
                except Exception:
                    reachable = False
            if not reachable:
                await self._on_actor_interrupted(actor, "GCS restarted")

    async def _wait_change(self, timeout: float) -> bool:
        """Wait until the next state change (or timeout); returns whether a
        change fired.  Callers re-check their condition in a loop."""
        if timeout <= 0:
            return False
        ev = self._change_event
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    # ---------------- node manager ----------------

    async def register_node(self, req):
        info: NodeInfo = req["info"]
        nid = info.node_id
        inc = int(getattr(info, "incarnation", 0) or 0)
        prev = self.nodes.get(nid)
        if prev is not None and not prev.alive:
            prev_inc = int(getattr(prev, "incarnation", 0) or 0)
            if inc <= prev_inc:
                # Split-brain fence: this node healed after we declared
                # it dead and failed its actors over.  Its gang is stale
                # — letting it back in as-is could double-apply updates
                # against the replacements.  Refuse, grant the next node
                # incarnation, and let the hostd fence itself (kill its
                # workers) before re-registering as the fresh incarnation.
                from ray_tpu.util import events
                events.record("gcs", "node_fenced", node=nid.hex()[:8],
                              stale_incarnation=inc,
                              granted_incarnation=prev_inc + 1)
                logger.warning(
                    "node %s re-registered after being declared dead; "
                    "fencing (stale incarnation %d, granting %d)",
                    nid.hex()[:8], inc, prev_inc + 1)
                return {"ok": False, "fenced": True,
                        "incarnation": prev_inc + 1}
        info.alive = True
        self.nodes[nid] = info
        self.node_heartbeat[nid] = time.monotonic()
        self._resync_pending.discard(nid)
        self._bump("nodes", nid)
        stale = await self._reconcile_node_snapshot(info,
                                                    req.get("snapshot"))
        logger.info("node %s registered at %s (%s, incarnation %d)",
                    nid.hex()[:8], info.address, info.resources_total, inc)
        return {"ok": True, "incarnation": inc, "stale_actors": stale}

    async def _reconcile_node_snapshot(self, info: NodeInfo, snapshot):
        """Anti-entropy against a re-registering node's ground truth.

        The snapshot lists what the hostd actually runs (live actor
        workers and their addresses, lease/worker counts).  Two ways the
        restored/stale tables can disagree, both fixed here: an actor we
        think is ALIVE on this node but the node no longer runs →
        interrupt it through the normal restart path; an actor the node
        still runs but we have failed over, killed, or never heard of →
        return it as stale so the hostd reaps that worker (the
        incarnation living at `address` lost ownership).
        """
        if not isinstance(snapshot, dict):
            return []
        reported: dict = {}
        for entry in snapshot.get("actors", ()):
            try:
                reported[entry["actor_id"]] = entry.get("address", "")
            except (TypeError, KeyError):
                continue
        stale = []
        for aid, addr in reported.items():
            a = self.actors.get(aid)
            if (a is None or a.state != "ALIVE" or a.node_id != info.node_id
                    or (addr and a.address != addr)):
                stale.append(aid)
        lost = 0
        for a in list(self.actors.values()):
            if a.state == "ALIVE" and a.node_id == info.node_id \
                    and a.actor_id not in reported:
                lost += 1
                await self._on_actor_interrupted(
                    a, "anti-entropy: node re-registered without the actor")
        if reported or stale or lost:
            from ray_tpu.util import events
            events.record("gcs", "node_resync",
                          node=info.node_id.hex()[:8],
                          reported=len(reported), stale=len(stale),
                          lost=lost)
        return stale

    async def heartbeat(self, req):
        """Typed (protocol.pb.HeartbeatRequest) or legacy dict."""
        from ray_tpu import protocol
        typed = protocol.is_message(req)
        if typed:
            nid = NodeID(req.node_id)
            available = dict(req.available.amounts)
        else:
            nid = req["node_id"]
            available = req["available"]

        def reply(*, reregister=False, shutdown=False):
            if typed:
                return protocol.pb.HeartbeatReply(
                    shutdown=shutdown, reregister=reregister)
            return {"ok": not reregister, "reregister": reregister,
                    "shutdown": shutdown}

        info = self.nodes.get(nid)
        if info is None or not info.alive or nid in self._resync_pending:
            return reply(reregister=True)
        self.node_heartbeat[nid] = time.monotonic()
        if info.resources_available != available:
            info.resources_available = available
            self._bump()
        return reply(shutdown=self._shutdown.is_set())

    async def get_nodes(self, req):
        return {"nodes": list(self.nodes.values()),
                "version": self._cluster_version,
                "boot_id": self.boot_id}

    async def add_task_events(self, req):
        """Sink for worker task-event buffers (reference: TaskEventBuffer
        task_event_buffer.h:188 streaming to GCS for observability)."""
        self._task_events.extend(req.get("events", []))
        overflow = len(self._task_events) - 20000
        if overflow > 0:
            del self._task_events[:overflow]
        return {"ok": True}

    async def pub_publish(self, req):
        """Publish messages to a channel (reference: publisher.h:302)."""
        channel = req["channel"]
        ring = self._channels.setdefault(channel, [])
        seq = self._channel_seq.get(channel, 0)
        for msg in req.get("messages", []):
            seq += 1
            ring.append((seq, msg))
        self._channel_seq[channel] = seq
        overflow = len(ring) - 10000
        if overflow > 0:
            del ring[:overflow]
        ev = self._channel_events.pop(channel, None)
        if ev is not None:
            ev.set()
        return {"seq": seq}

    async def pub_poll(self, req):
        """Long-poll a channel past after_seq (reference: long-poll
        subscriber channels, subscriber.h:70): holds the request until a
        publish or timeout."""
        channel = req["channel"]
        after = req.get("after_seq", 0)
        deadline = time.monotonic() + req.get("timeout_s", 10.0)
        import bisect
        while True:
            ring = self._channels.get(channel, [])
            start = bisect.bisect_right(ring, after, key=lambda e: e[0])
            if start < len(ring):
                return {"messages": ring[start:],
                        "seq": self._channel_seq.get(channel, 0)}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"messages": [],
                        "seq": self._channel_seq.get(channel, 0)}
            ev = self._channel_events.get(channel)
            if ev is None:
                ev = self._channel_events[channel] = asyncio.Event()
            try:
                await asyncio.wait_for(ev.wait(), min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass

    async def add_log_lines(self, req):
        """Worker-log sink (reference: log lines flow to the driver over
        GCS pubsub, _private/gcs_pubsub.py)."""
        for rec in req.get("lines", []):
            self._log_seq += 1
            self._log_lines.append((self._log_seq, rec))
        overflow = len(self._log_lines) - 10000
        if overflow > 0:
            del self._log_lines[:overflow]
        return {"ok": True, "seq": self._log_seq}

    async def get_log_lines(self, req):
        after = req.get("after_seq", 0)
        job = req.get("job_id")
        # Ring is seq-ordered: bisect to the first unseen entry instead of
        # scanning 10k records per poll per driver.
        import bisect
        start = bisect.bisect_right(
            self._log_lines, after, key=lambda e: e[0])
        out = [(seq, rec) for seq, rec in self._log_lines[start:]
               if job is None or rec.get("job_id") == job]
        return {"lines": out, "seq": self._log_seq}

    async def get_task_events(self, req):
        limit = req.get("limit", 10000)
        if limit <= 0:
            return {"events": []}
        return {"events": self._task_events[-limit:]}

    async def get_metrics(self, req):
        from ray_tpu.util import metrics as mt
        _metrics()["nodes_alive"].set(
            sum(1 for n in self.nodes.values() if n.alive))
        return {"metrics": mt.collect()}

    async def drain_node(self, req):
        await self._mark_node_dead(req["node_id"], "drained")
        return {"ok": True}

    async def _mark_node_dead(self, nid: NodeID, reason: str):
        info = self.nodes.get(nid)
        if info is None or not info.alive:
            return
        info.alive = False
        self._bump("nodes", nid)
        logger.warning("node %s dead: %s", nid.hex()[:8], reason)
        # Fail over actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == nid and actor.state in ("ALIVE", "PENDING"):
                await self._on_actor_interrupted(actor, f"node died: {reason}")
        # Re-place bundles that lived there.
        self._reschedule_pgs_for_dead_node(nid)

    async def _health_loop(self):
        while not self._shutdown.is_set():
            now = time.monotonic()
            for nid, last in list(self.node_heartbeat.items()):
                info = self.nodes.get(nid)
                if info is not None and info.alive and not info.is_head \
                        and now - last > NODE_DEATH_TIMEOUT_S:
                    await self._mark_node_dead(nid, "heartbeat timeout")
            await asyncio.sleep(HEARTBEAT_INTERVAL_S)

    # ---------------- job manager ----------------

    async def next_job_id(self, req):
        async with self._job_lock:
            self.next_job += 1
            # ("meta", None) survives to the flush (which always writes
            # the meta rows) but matches no live table row.
            self._mark_dirty("meta", None)
            return {"job_id": self.next_job}

    # ---------------- actor manager ----------------
    # Two-phase as in the reference (gcs_actor_manager.h:249): RegisterActor
    # persists the record, CreateActor drives scheduling.  We fuse the
    # scheduling trigger into register for simplicity but keep the externally
    # visible states PENDING -> ALIVE (-> RESTARTING) -> DEAD.

    async def register_actor(self, req):
        info: ActorInfo = req["info"]
        if info.name:
            key = (info.namespace, info.name)
            existing_id = self.named_actors.get(key)
            if existing_id is not None:
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != "DEAD":
                    if req.get("get_if_exists"):
                        return {"existing": existing}
                    raise ValueError(
                        f"actor name {info.name!r} already taken in "
                        f"namespace {info.namespace!r}")
            self.named_actors[key] = info.actor_id
            self._mark_dirty("named_actors", key)
        self.actors[info.actor_id] = info
        self._mark_dirty("actors", info.actor_id)
        asyncio.ensure_future(self._schedule_actor(info))
        return {"existing": None}

    async def _schedule_actor(self, info: ActorInfo):
        """Lease a dedicated worker on some node and run the creation task."""
        demand = info.resources.to_dict()
        # Pick with >=1 CPU so default actors land on nodes with headroom,
        # but reserve only the declared demand (1-for-scheduling /
        # 0-for-running, as in the reference).
        pick_demand = demand or {"CPU": 1.0}
        spec = info.creation_spec
        pg_id = spec.placement_group if spec is not None else None
        tried: set[NodeID] = set()
        attempt = 0
        # PG actors pend until the PG is removed (reference: PG-scheduled
        # work queues on the bundle indefinitely); non-PG actors give up
        # after 100 placement attempts.
        while pg_id is not None or attempt < 100:
            attempt += 1
            if info.state == "DEAD":
                return
            bundle = None
            if pg_id is not None:
                pg = self.placement_groups.get(pg_id)
                if pg is None or pg.state == "REMOVED":
                    info.state = "DEAD"
                    info.death_cause = "placement group unavailable"
                    info.version += 1
                    return
                if pg.state != "CREATED":
                    await self._wait_change(0.1)
                    continue
                idx = spec.bundle_index
                if idx >= len(pg.bundles):
                    info.state = "DEAD"
                    info.death_cause = (f"bundle index {idx} out of range "
                                        f"({len(pg.bundles)} bundles)")
                    info.version += 1
                    return

                def fits(b: dict) -> bool:
                    return all(b.get(k, 0.0) + 1e-9 >= v
                               for k, v in demand.items() if v > 0)

                candidates = [idx] if idx >= 0 else \
                    [i for i in range(len(pg.bundles)) if fits(pg.bundles[i])]
                if (idx >= 0 and not fits(pg.bundles[idx])) or not candidates:
                    info.state = "DEAD"
                    info.death_cause = (
                        f"actor demands {demand}, which exceeds "
                        f"{'bundle %d' % idx if idx >= 0 else 'every bundle'}"
                        f" of its placement group")
                    info.version += 1
                    return
                if idx < 0:
                    # Rotate across feasible bundles so concurrent actors
                    # spread out and a full bundle doesn't starve the rest.
                    idx = candidates[(attempt - 1 + info.num_restarts)
                                     % len(candidates)]
                node = self.nodes.get(pg.bundle_nodes[idx])
                if node is None or not node.alive:
                    await self._wait_change(0.2)
                    continue
                bundle = (pg_id.hex(), idx)
            else:
                node = sched.pick_node(self._alive_nodes(), pick_demand,
                                       strategy="DEFAULT", exclude=tried)
            if node is None:
                await self._wait_change(0.2)  # wait for capacity/new nodes
                tried.clear()
                continue
            job_int = int.from_bytes(
                info.creation_spec.job_id.binary(), "little") \
                if info.creation_spec is not None else 0
            try:
                lease = await self.pool.get(node.address).call(
                    "NodeManager", "LeaseWorkerForActor",
                    {"actor_id": info.actor_id, "resources": demand,
                     "job_id": job_int, "bundle": bundle,
                     "runtime_env": getattr(info.creation_spec,
                                            "runtime_env", None)
                     if info.creation_spec is not None else None},
                    timeout=45)  # > the hostd's 30s lease queue window
            except Exception as e:
                logger.info("lease on %s failed: %s", node.address, e)
                tried.add(node.node_id)
                # Back off on transport errors too: a spin here burns the
                # attempt budget in seconds when the sole node's daemon
                # is briefly unreachable (storm overload, restart).
                await self._wait_change(0.2)
                continue
            if not lease.get("granted"):
                if lease.get("reason") in ("busy", "resources"):
                    # Saturation is not a placement failure: the node
                    # queued us for its whole lease window and is still
                    # full.  Actors PEND until capacity exists
                    # (reference: GCS actor scheduler retries leases
                    # indefinitely while the raylet queues) — don't burn
                    # the attempt budget, don't spin.
                    attempt -= 1
                    await self._wait_change(0.2)
                else:
                    tried.add(node.node_id)
                    if pg_id is not None:
                        await self._wait_change(0.2)
                continue
            worker_addr = lease["worker_address"]
            try:
                reply = await self._push_create(
                    worker_addr, lease.get("native_port", 0),
                    info.creation_spec)
            except Exception as e:
                logger.warning("actor %s creation push failed: %s",
                               info.actor_id.hex()[:8], e)
                tried.add(node.node_id)
                continue
            if info.state == "DEAD":
                # Killed while we were scheduling it: don't resurrect; tear
                # down the worker we just created it on.
                try:
                    await self.pool.get(worker_addr).call(
                        "CoreWorker", "KillActor",
                        {"actor_id": info.actor_id, "no_restart": True},
                        timeout=5)
                except Exception:
                    pass
                return
            if reply.get("error") is not None:
                info.state = "DEAD"
                info.death_cause = f"creation failed: {reply['error']}"
                info.version += 1
                self._bump("actors", info.actor_id)
                return
            info.state = "ALIVE"
            info.address = worker_addr
            info.native_port = lease.get("native_port", 0)
            info.node_id = node.node_id
            info.version += 1
            _metrics()["actors_created"].inc()
            self._bump("actors", info.actor_id)
            logger.info("actor %s alive at %s", info.actor_id.hex()[:8],
                        worker_addr)
            return
        info.state = "DEAD"
        info.death_cause = "scheduling failed after 100 attempts"
        info.version += 1
        self._bump("actors", info.actor_id)

    async def _push_create(self, worker_addr: str, native_port: int,
                           spec):
        """Push the creation task to the freshly leased worker over the
        native plane when it advertises one (a PushTaskRequest proto,
        spec_codec — the same typed wire contract task submission
        speaks; no per-worker gRPC channel in the GCS), falling back to
        the CreateActor RPC."""
        if native_port:
            from ray_tpu._private import spec_codec
            from ray_tpu._private.task_transport import (
                ConnClosedError,
                NativeSubmitter,
            )
            try:
                if self._native_sub is None:
                    self._native_sub = NativeSubmitter(
                        asyncio.get_running_loop())
                    self._native_sub.set_caller(b"gcs")
                naddr = (f"{worker_addr.rsplit(':', 1)[0]}:{native_port}")
                payload = spec_codec.push_request_to_wire(spec, b"gcs", 0)
                data = await asyncio.wait_for(
                    self._native_sub.call(naddr, payload), 120)
                return spec_codec.reply_from_wire(data)
            except (ConnClosedError, ConnectionError):
                # The worker never (completely) received the push: safe
                # to fall back to the RPC path on the same worker.
                logger.info("native creation push connection failed; "
                            "falling back to RPC")
            # Any other failure (timeout included) may have DELIVERED the
            # creation — a same-worker fallback would run __init__ twice
            # in one process.  Surface it; the scheduler retries on a
            # different worker like a failed RPC.
        return await self.pool.get(worker_addr).call(
            "CoreWorker", "CreateActor",
            {"spec": spec, "actor_id": spec.actor_id}, timeout=120)

    async def _on_actor_interrupted(self, actor: ActorInfo, reason: str):
        if actor.num_restarts < actor.max_restarts or actor.max_restarts == -1:
            actor.num_restarts += 1
            _metrics()["actor_restarts"].inc()
            actor.state = "RESTARTING"
            actor.address = ""
            actor.version += 1
            self._bump("actors", actor.actor_id)
            logger.info("restarting actor %s (%d/%s): %s",
                        actor.actor_id.hex()[:8], actor.num_restarts,
                        actor.max_restarts, reason)
            asyncio.ensure_future(self._schedule_actor(actor))
        else:
            actor.state = "DEAD"
            actor.death_cause = reason
            actor.address = ""
            actor.version += 1
            self._bump("actors", actor.actor_id)

    async def report_actor_death(self, req):
        actor = self.actors.get(req["actor_id"])
        # Incarnation guard: a corpse report names the worker address it
        # died at.  If the actor has already been restarted elsewhere
        # (fast restarts outrun the ~0.2s corpse sweep), the stale report
        # must not consume another restart — or kill the live actor.
        dead_addr = req.get("address")
        if (actor is not None and dead_addr and actor.address
                and dead_addr != actor.address):
            return {"ok": True, "stale": True}
        if actor is not None and actor.state in ("ALIVE", "PENDING"):
            if req.get("intentional"):
                actor.state = "DEAD"
                actor.death_cause = req.get("reason", "killed")
                actor.address = ""
                actor.version += 1
                self._bump("actors", actor.actor_id)
            else:
                await self._on_actor_interrupted(actor, req.get("reason", "?"))
        return {"ok": True}

    async def get_actor_info(self, req):
        aid = req["actor_id"]
        actor = self.actors.get(aid)
        # Long-poll: while the actor is pending/restarting — or not yet
        # registered at all (registration is async; a handle can be
        # resolved by a borrower before the owner's register lands) —
        # hold the request briefly so callers don't spin (reference:
        # pubsub long-poll).  Parked on a PER-ACTOR event: unrelated
        # cluster changes must not wake every parked poll.
        deadline = time.monotonic() + req.get("wait_s", 0)
        try:
            while (actor is None
                   or actor.state in ("PENDING", "RESTARTING")) \
                    and time.monotonic() < deadline:
                ev = self._actor_events.get(aid)
                if ev is None:
                    ev = self._actor_events[aid] = asyncio.Event()
                try:
                    await asyncio.wait_for(
                        ev.wait(), min(0.5, deadline - time.monotonic()))
                except asyncio.TimeoutError:
                    pass
                actor = self.actors.get(aid)
        finally:
            if self.actors.get(aid) is None:
                # Never-registered id: no _bump will ever pop the entry;
                # drop it so stale/garbage ids can't grow the dict.
                # Concurrent pollers of the same id just re-create it on
                # their next loop iteration.
                self._actor_events.pop(aid, None)
        return {"info": actor}

    async def get_named_actor(self, req):
        aid = self.named_actors.get((req.get("namespace", "default"), req["name"]))
        return {"info": self.actors.get(aid) if aid else None}

    async def list_actors(self, req):
        return {"actors": list(self.actors.values())}

    async def kill_actor(self, req):
        actor = self.actors.get(req["actor_id"])
        if actor is None:
            return {"ok": False}
        no_restart = req.get("no_restart", True)
        address = actor.address
        if no_restart:
            actor.state = "DEAD"
            actor.death_cause = "ray_tpu.kill"
            actor.address = ""
            actor.version += 1
            self._bump("actors", actor.actor_id)
        else:
            # Kill the process but honor max_restarts (reference:
            # ray.kill(no_restart=False) semantics).
            await self._on_actor_interrupted(actor, "ray_tpu.kill(no_restart=False)")
        if address:
            try:
                await self.pool.get(address).call(
                    "CoreWorker", "KillActor",
                    {"actor_id": req["actor_id"], "no_restart": no_restart},
                    timeout=5)
            except Exception:
                pass
        return {"ok": True}

    # ---------------- placement-group manager ----------------
    # Reference: gcs_placement_group_manager.h (lifecycle) +
    # gcs_placement_group_scheduler.h (bundle placement + 2PC against the
    # per-node daemons).  Strategies: placement_group.h PACK/SPREAD/
    # STRICT_PACK/STRICT_SPREAD.

    async def create_placement_group(self, req):
        info: PlacementGroupInfo = req["info"]
        if not info.bundle_nodes:
            info.bundle_nodes = [None] * len(info.bundles)
            info.bundle_addresses = [""] * len(info.bundles)
        self.placement_groups[info.pg_id] = info
        self._mark_dirty("placement_groups", info.pg_id)
        asyncio.ensure_future(self._schedule_pg(info))
        return {"ok": True}

    def _plan_bundles(self, info: PlacementGroupInfo):
        """Choose a node for every unplaced bundle against a scratch copy of
        the cluster's available resources.  Returns {index: NodeInfo} or
        None when currently infeasible."""
        nodes = self._alive_nodes()
        scratch = {n.node_id: dict(n.resources_available) for n in nodes}
        by_id = {n.node_id: n for n in nodes}
        used_nodes = {nid for nid in info.bundle_nodes if nid is not None}
        pending = [i for i, nid in enumerate(info.bundle_nodes) if nid is None]

        def fits(nid, demand):
            avail = scratch[nid]
            return all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items() if v > 0)

        def take(nid, demand):
            for k, v in demand.items():
                if v > 0:
                    scratch[nid][k] = scratch[nid].get(k, 0.0) - v

        plan = {}
        if info.strategy == "STRICT_PACK":
            # All bundles on ONE node (for TPU: one bundle group = one host;
            # a slice-atomic unit).
            anchor = next(iter(used_nodes), None)
            candidates = ([by_id[anchor]] if anchor in by_id else nodes)
            for node in candidates:
                trial = dict(scratch[node.node_id])
                ok = True
                for i in pending:
                    d = info.bundles[i]
                    if all(trial.get(k, 0.0) + 1e-9 >= v
                           for k, v in d.items() if v > 0):
                        for k, v in d.items():
                            if v > 0:
                                trial[k] = trial.get(k, 0.0) - v
                    else:
                        ok = False
                        break
                if ok:
                    for i in pending:
                        plan[i] = node
                    return plan
            return None

        prefer_spread = info.strategy in ("SPREAD", "STRICT_SPREAD")
        for i in pending:
            demand = info.bundles[i]
            cands = [n for n in nodes if fits(n.node_id, demand)]
            if prefer_spread:
                fresh = [n for n in cands
                         if n.node_id not in used_nodes
                         and n.node_id not in {p.node_id for p in plan.values()}]
                if fresh:
                    cands = fresh
                elif info.strategy == "STRICT_SPREAD":
                    return None
                # Spread: least-utilized first.
                cands.sort(key=lambda n: -sum(scratch[n.node_id].values()))
            else:
                # PACK: prefer nodes already carrying bundles of this PG.
                cands.sort(key=lambda n: (
                    n.node_id not in used_nodes
                    and n.node_id not in {p.node_id for p in plan.values()},
                    sum(scratch[n.node_id].values())))
            if not cands:
                return None
            node = cands[0]
            take(node.node_id, demand)
            plan[i] = node
        return plan

    async def _schedule_pg(self, info: PlacementGroupInfo):
        # Pends until satisfiable or removed (reference: PGs wait for
        # capacity indefinitely — e.g. created ahead of autoscaling).
        while info.state != "REMOVED":
            plan = self._plan_bundles(info)
            if not plan:
                await self._wait_change(0.2)
                continue
            # Phase 1: prepare every bundle; roll back all on any failure.
            prepared = []
            ok = True
            for i, node in plan.items():
                try:
                    r = await self.pool.get(node.address).call(
                        "NodeManager", "PrepareBundle",
                        {"pg_id": info.pg_id.hex(), "index": i,
                         "resources": info.bundles[i]}, timeout=10)
                except Exception:
                    ok = False
                    break
                if not r.get("ok"):
                    ok = False
                    break
                prepared.append((i, node))
            if not ok:
                # Roll back on EVERY planned node, not just confirmed
                # prepares: a Prepare whose reply was lost still reserved
                # server-side (CancelBundle on an unprepared key is a no-op).
                await self._cancel_bundles_on(plan.items(), info)
                await self._wait_change(0.2)
                continue
            # Phase 2: commit.  A failed commit on a live node leaves the
            # bundle unusable (leases check committed=True) — cancel it and
            # re-place rather than shipping a wedged CREATED group.
            failed = []
            for i, node in plan.items():
                try:
                    await self.pool.get(node.address).call(
                        "NodeManager", "CommitBundle",
                        {"pg_id": info.pg_id.hex(), "index": i}, timeout=10)
                except Exception:
                    failed.append((i, node))
                    continue
                info.bundle_nodes[i] = node.node_id
                info.bundle_addresses[i] = node.address
            if info.state == "REMOVED":
                # Removed while we were preparing/committing: the removal
                # saw empty bundle_nodes and had nothing to cancel — undo
                # everything we just reserved.
                await self._cancel_bundles_on(plan.items(), info)
                return
            if failed:
                await self._cancel_bundles_on(failed, info)
                await self._wait_change(0.2)
                continue
            # A planned node may have died while prepare/commit RPCs were in
            # flight — its death event fired before bundle_nodes was written,
            # so _reschedule_pgs_for_dead_node saw nothing.  Re-check here.
            lost = [i for i, nid in enumerate(info.bundle_nodes)
                    if nid is not None and (
                        self.nodes.get(nid) is None
                        or not self.nodes[nid].alive)]
            if lost:
                for i in lost:
                    info.bundle_nodes[i] = None
                    info.bundle_addresses[i] = ""
                await self._wait_change(0.2)
                continue
            info.state = "CREATED"
            info.version += 1
            _metrics()["placement_groups_created"].inc()
            self._bump("placement_groups", info.pg_id)
            logger.info("placement group %s created (%d bundles)",
                        info.pg_id.hex()[:8], len(info.bundles))
            return

    async def _cancel_bundles_on(self, pairs, info: PlacementGroupInfo):
        for i, node in pairs:
            try:
                await self.pool.get(node.address).call(
                    "NodeManager", "CancelBundle",
                    {"pg_id": info.pg_id.hex(), "index": i}, timeout=10)
            except Exception:
                pass
            info.bundle_nodes[i] = None
            info.bundle_addresses[i] = ""

    async def remove_placement_group(self, req):
        info = self.placement_groups.get(req["pg_id"])
        if info is None:
            return {"ok": False}
        info.state = "REMOVED"
        info.version += 1
        self._bump("placement_groups", info.pg_id)
        nodes = {nid for nid in info.bundle_nodes if nid is not None}
        for nid in nodes:
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                continue
            try:
                await self.pool.get(node.address).call(
                    "NodeManager", "CancelBundle",
                    {"pg_id": info.pg_id.hex()}, timeout=10)
            except Exception:
                pass
        # Actors created inside the PG die with it (reference semantics).
        for actor in list(self.actors.values()):
            spec = actor.creation_spec
            if spec is not None and spec.placement_group == info.pg_id \
                    and actor.state != "DEAD":
                await self.kill_actor({"actor_id": actor.actor_id,
                                       "no_restart": True})
        return {"ok": True}

    async def cleanup_job(self, req):
        """Driver exit: tear down the job's non-detached placement groups
        (reference: GcsPlacementGroupManager::CleanPlacementGroupIfNeeded-
        WhenJobDead) and its non-detached actors."""
        job = req["job_id"]
        removed = 0
        for info in list(self.placement_groups.values()):
            if info.creator_job == job and not info.lifetime_detached \
                    and info.state != "REMOVED":
                await self.remove_placement_group({"pg_id": info.pg_id})
                removed += 1
        for actor in list(self.actors.values()):
            spec = actor.creation_spec
            if spec is not None and int.from_bytes(
                    spec.job_id.binary(), "little") == job \
                    and not actor.lifetime_detached \
                    and actor.state not in ("DEAD",):
                await self.kill_actor({"actor_id": actor.actor_id,
                                       "no_restart": True})
        return {"ok": True, "removed_pgs": removed}

    async def get_placement_group(self, req):
        info = self.placement_groups.get(req["pg_id"])
        deadline = time.monotonic() + req.get("wait_s", 0)
        while info is not None and info.state in ("PENDING", "RESCHEDULING") \
                and time.monotonic() < deadline:
            await self._wait_change(min(0.5, deadline - time.monotonic()))
        return {"info": info}

    async def list_placement_groups(self, req):
        return {"placement_groups": list(self.placement_groups.values())}

    def _reschedule_pgs_for_dead_node(self, nid: NodeID):
        for info in self.placement_groups.values():
            if info.state not in ("CREATED", "RESCHEDULING", "PENDING"):
                continue
            lost = [i for i, b in enumerate(info.bundle_nodes) if b == nid]
            if not lost:
                continue
            for i in lost:
                info.bundle_nodes[i] = None
                info.bundle_addresses[i] = ""
            if info.state == "CREATED":
                info.state = "RESCHEDULING"
                info.version += 1
                asyncio.ensure_future(self._schedule_pg(info))

    # ---------------- scheduling service ----------------

    async def pick_node(self, req):
        node = sched.pick_node(
            self._alive_nodes(), req["resources"],
            strategy=req.get("strategy", "DEFAULT"),
            exclude=set(req.get("exclude") or ()),
            affinity=req.get("node_affinity"),
            affinity_soft=req.get("node_affinity_soft", True),
            locality=req.get("locality"),
        )
        return {"node": node}

    def _alive_nodes(self) -> list[NodeInfo]:
        return [n for n in self.nodes.values() if n.alive]

    # ---------------- cluster lifecycle ----------------

    async def cluster_resources(self, req):
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        for n in self._alive_nodes():
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def shutdown_cluster(self, req):
        self._shutdown.set()
        return {"ok": True}

    async def ping(self, req):
        return {"ok": True, "version": self._cluster_version}

    async def collect_events(self, req):
        """Own flight-recorder ring.  The GCS is its own process — no
        hostd scrapes it — so without this the `gcs/flush` spans and
        actor-manager events would be invisible to state.events()."""
        from ray_tpu.util import events as ev
        return {"events": ev.snapshot(since=req.get("since", 0.0)),
                "now": time.time()}

    # ---------------- lifecycle ----------------

    def _arm_chaos_kill(self):
        """Scripted head kill: wrap every registered control-plane handler
        so this GCS incarnation can os._exit(1) right before serving its
        `chaos_kill_gcs_at`-th request.  Which operation lands on that
        ordinal is scenario-determined — a heartbeat, a PG schedule, a KV
        put — which is the point: the supervised restart must absorb a
        death at ANY request boundary.  The flight ring is dumped first so
        `cli analyze` can reconstruct what the head was doing when it
        died."""
        from ray_tpu._private.fault_injection import get_chaos
        if get_chaos() is None:
            return
        from ray_tpu.util import events

        def wrap(path, fn):
            async def wrapped(request):
                chaos = get_chaos()
                if chaos is not None and chaos.kill_gcs():
                    events.record("gcs", "chaos_kill", method=path)
                    events.dump_crash("chaos_kill_gcs")
                    os._exit(1)
                return await fn(request)
            return wrapped

        for path, fn in list(self.server._methods.items()):
            self.server._methods[path] = wrap(path, fn)

    async def start(self, port: int = 0) -> int:
        self.server.register_service("Kv", self.kv)
        self.server.register_service("Gcs", self)
        self._arm_chaos_kill()
        self._restore()
        port = await self.server.start(port)
        self._health_task = asyncio.ensure_future(self._health_loop())
        return port

    async def run_until_shutdown(self):
        await self._shutdown.wait()
        await asyncio.sleep(2 * HEARTBEAT_INTERVAL_S)  # let hostds see it
        await self.server.stop()
        await self.pool.close_all()
        if self._native_sub is not None:
            try:
                self._native_sub.close()
            except Exception:
                pass


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--ready-file", default="")
    parser.add_argument("--watch-pid", type=int, default=0,
                        help="exit when this process disappears "
                             "(driver-embedded clusters)")
    args = parser.parse_args()
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOGLEVEL", "INFO"))

    if args.watch_pid:
        import threading
        import time as _time

        def _watch():
            while True:
                try:
                    os.kill(args.watch_pid, 0)
                except ProcessLookupError:
                    logger.warning("driver %d gone; GCS exiting",
                                   args.watch_pid)
                    os._exit(0)
                except PermissionError:
                    pass
                _time.sleep(1.0)

        threading.Thread(target=_watch, daemon=True,
                         name="driver-watch").start()

    from ray_tpu._private.profiling import start_periodic_profile
    start_periodic_profile("RAY_TPU_PROFILE_GCS", "gcs")

    async def run():
        gcs = GcsServer(args.host)
        port = await gcs.start(args.port)
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.ready_file)
        logger.info("GCS listening on %s:%d", args.host, port)
        await gcs.run_until_shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
