"""Deterministic fault injection (chaos) for the whole runtime.

Reference parity: Ray's testing_asio_delay_us / RAY_testing_rpc_failure
knobs (src/ray/common/ray_config_def.h) plus the chaos-mesh style kill
tests in python/ray/tests/test_failure*.py — here unified behind one
seeded controller so an injected-fault schedule is a *pure function of
the seed*, independent of thread timing.

Three planes are interposed:

- ``rpc``    — every outbound `RpcClient.call` (drop / delay / disconnect)
- ``native`` — the framed-TCP task plane (`task_transport.NativeSubmitter`)
  and the object-transfer fetch path (`object_transfer.fetch`)
- ``proc``   — process lifetime: worker self-kill before task execution
  (`core_worker._execute_task`) and hostd self-kill in its heartbeat loop

Determinism: each plane keeps a monotonically increasing event index, and
the decision for event *n* on plane *p* is drawn from
``random.Random(f"{seed}|{p}|{n}")`` — a fresh PRNG keyed by (seed, plane,
index).  Two runs with the same seed therefore inject the *same* fault at
the *same* per-plane event ordinal even when threads interleave
differently; only the index allocation (which call gets which ordinal)
needs to match, which holds per-plane because each interposition point
increments under a lock.

All flags live in `_private.config` (``RAY_TPU_CHAOS_*`` env vars /
``_system_config={"chaos_enabled": True, ...}``) and propagate to spawned
daemons and workers via the env-var export in `api.init`.  With
``chaos_enabled`` off (the default) `get_chaos()` returns None and the
hot paths pay a single attribute read.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import List, Optional, Tuple

from .config import GLOBAL_CONFIG

# The GCS address, registered by whoever builds a GcsClient, so partition
# rules can name the head symbolically ("h2>gcs@...") instead of by the
# ephemeral host:port the session happened to bind.
_gcs_address: Optional[str] = None


def set_gcs_address(address: str) -> None:
    """Label `address` as "gcs" for link-partition rule matching."""
    global _gcs_address
    _gcs_address = address


class ChaosInjectedError(ConnectionError):
    """A fault injected by the chaos layer.

    Subclasses ConnectionError so injected faults ride the exact same
    retry / failover paths as real transport failures — the point of the
    exercise is that recovery code cannot tell them apart.
    """


class ChaosController:
    """Seeded fault scheduler; one per process.

    ``should(plane, probability)`` allocates the next event index on
    `plane` and returns the deterministic verdict for that (seed, plane,
    index) triple.  Every injected fault is appended to ``schedule`` as
    ``(plane, index, kind)`` so tests can assert that two controllers
    with the same seed produce identical schedules.
    """

    def __init__(self, seed: int, max_faults: int = 0,
                 salt: str | None = None):
        self.seed = int(seed)
        self.max_faults = int(max_faults)  # 0 = unlimited
        # Process identity salt: hostd stamps each worker with its spawn
        # ordinal (RAY_TPU_CHAOS_PROC_SALT).  Without it a killed worker's
        # replacement would replay the exact draw that killed its
        # predecessor (same seed, same fresh counters) and die forever;
        # with it, the replacement draws a distinct — still seed-
        # deterministic — schedule.  Daemons and the driver carry no salt.
        self.salt = (os.environ.get("RAY_TPU_CHAOS_PROC_SALT", "")
                     if salt is None else salt)
        self._counters: dict = {}
        self._faults = 0
        self._lock = threading.Lock()
        self.schedule: List[Tuple[str, int, str]] = []
        # Link-partition plane state: parsed rules (cached per spec
        # string) and per-destination call ordinals.
        self._link_spec: Optional[str] = None
        self._link_rules: List[dict] = []

    # -- deterministic draws ----------------------------------------------

    def _next_index(self, plane: str) -> int:
        n = self._counters.get(plane, 0)
        self._counters[plane] = n + 1
        return n

    def draw(self, plane: str, index: int) -> float:
        """The uniform [0,1) draw for event `index` on `plane` — a pure
        function of (seed, salt, plane, index)."""
        return random.Random(
            f"{self.seed}|{self.salt}|{plane}|{index}").random()

    def should(self, plane: str, probability: float, kind: str) -> bool:
        """Allocate the next event on `plane`; True if a fault fires.

        Respects ``max_faults``: once the budget is exhausted no further
        faults fire (so chaos tests converge instead of flapping forever),
        but indices keep advancing so the schedule stays aligned.
        """
        if probability <= 0.0:
            with self._lock:
                self._next_index(plane)
            return False
        with self._lock:
            n = self._next_index(plane)
            if self.max_faults and self._faults >= self.max_faults:
                return False
            hit = self.draw(plane, n) < probability
            if hit:
                self._faults += 1
                self.schedule.append((plane, n, kind))
            return hit

    @property
    def faults_injected(self) -> int:
        with self._lock:
            return self._faults

    # -- plane-specific policy (reads config each call: flags are cached
    # in the registry, and tests flip them between scenarios) -------------

    def rpc_fault(self) -> Optional[Tuple[str, float]]:
        """Chaos verdict for one outbound RPC attempt.

        Returns None (no fault) or ("drop"|"disconnect", 0.0) /
        ("delay", seconds).  Drop and disconnect surface as
        ChaosInjectedError at the call site; delay just sleeps.
        """
        cfg = GLOBAL_CONFIG
        if self.should("rpc", cfg.chaos_rpc_drop, "drop"):
            return ("drop", 0.0)
        if self.should("rpc", cfg.chaos_rpc_disconnect, "disconnect"):
            return ("disconnect", 0.0)
        if self.should("rpc", cfg.chaos_rpc_delay_p, "delay"):
            return ("delay", cfg.chaos_rpc_delay_ms / 1000.0)
        return None

    def native_drop(self) -> bool:
        """Drop one native-transport task push."""
        return self.should("native", GLOBAL_CONFIG.chaos_native_drop, "drop")

    def object_fetch_drop(self) -> bool:
        """Fail one object-transfer fetch (simulates a lost copy)."""
        return self.should(
            "object", GLOBAL_CONFIG.chaos_object_fetch_drop, "drop")

    def kill_worker(self) -> bool:
        """Kill this worker process before executing the next task.

        Two modes (ISSUE: "probabilistic or scripted kills"):
        - scripted: `chaos_kill_worker_salts` names worker spawn ordinals
          (csv); a listed worker dies right before executing its
          `chaos_kill_worker_at`-th task.  Fully deterministic AND
          convergent — the replacement worker has the next ordinal, which
          is not in the list.
        - probabilistic: `chaos_kill_worker` per-execution probability,
          drawn from the salted (seed, plane, index) stream.
        """
        cfg = GLOBAL_CONFIG
        salts = str(cfg.chaos_kill_worker_salts or "")
        if salts and self.salt:
            listed = self.salt in [s.strip() for s in salts.split(",")]
            with self._lock:
                n = self._next_index("proc")
                if listed and n == int(cfg.chaos_kill_worker_at):
                    self._faults += 1
                    self.schedule.append(("proc", n, "kill"))
                    return True
            return False
        return self.should("proc", cfg.chaos_kill_worker, "kill")

    def kill_replica(self) -> bool:
        """Kill this serve replica process at a serve-plane event (a
        request dispatch or a stream-chunk pull) — the mid-generation
        death the serve failover path must absorb.

        Same two modes as kill_worker: scripted
        (`chaos_kill_replica_salts` lists worker spawn ordinals, or
        ``*`` for "any serve replica process"; a listed replica dies at
        its `chaos_kill_replica_at`-th serve event) or probabilistic
        (`chaos_kill_replica` per event).  Unlike kill_worker, the
        scripted mode DOES respect `chaos_max_faults`: with the ``*``
        wildcard every replacement replica re-arms at the same event
        ordinal, so the faults budget is what makes a scripted scenario
        convergent."""
        cfg = GLOBAL_CONFIG
        salts = str(cfg.chaos_kill_replica_salts or "")
        if salts:
            listed = (salts.strip() == "*"
                      or (self.salt and self.salt in
                          [s.strip() for s in salts.split(",")]))
            with self._lock:
                n = self._next_index("serve")
                if (listed and n == int(cfg.chaos_kill_replica_at)
                        and not (self.max_faults
                                 and self._faults >= self.max_faults)):
                    self._faults += 1
                    self.schedule.append(("serve", n, "kill"))
                    return True
            return False
        return self.should("serve", cfg.chaos_kill_replica, "kill")

    def kill_hostd(self, is_head: bool = False) -> bool:
        """Kill this node daemon at a heartbeat tick.

        Two modes, like the serve/ckpt/preempt planes:

        - scripted: `chaos_kill_hostd_salts` lists hostd spawn ordinals
          ("h1", "h2", ... as stamped by node.start_hostd, or ``*`` for
          any non-head hostd); a listed hostd dies at exactly its
          `chaos_kill_hostd_at`-th heartbeat tick — the deterministic
          way to lose one specific node of a multi-node cluster at a
          known instant (the pipeline-under-node-loss gate).  A salt
          match targets the named hostd even if it is the head; the
          ``*`` wildcard never hits the head (killing the colocated GCS
          just ends the test).  Respects `chaos_max_faults` so a
          respawned/replacement hostd cannot re-fire forever.
        - probabilistic: `chaos_kill_hostd` per tick, never on the head.

        The tick ordinal advances on every call in both modes and on
        head nodes too, so one (seed, salt) schedule reads the same
        whichever mode is active.
        """
        cfg = GLOBAL_CONFIG
        salts = str(cfg.chaos_kill_hostd_salts or "")
        if salts:
            listed = ((salts.strip() == "*" and not is_head)
                      or (self.salt and self.salt in
                          [s.strip() for s in salts.split(",")]))
            with self._lock:
                n = self._next_index("hostd")
                if (listed and n == int(cfg.chaos_kill_hostd_at)
                        and not (self.max_faults
                                 and self._faults >= self.max_faults)):
                    self._faults += 1
                    self.schedule.append(("hostd", n, "kill"))
                    return True
            return False
        if is_head:
            with self._lock:
                self._next_index("hostd")
            return False
        return self.should("hostd", cfg.chaos_kill_hostd, "kill")

    def preempt_hostd(self, is_head: bool) -> bool:
        """Inject a preemption NOTICE at a hostd heartbeat tick — the
        maintenance-event simulation the train plane's grace-window save
        must race.  Unlike kill_hostd this fires on head nodes too: a
        preempted head degrades to killing only its workers (slice
        loss), so the colocated GCS survives and the scenario stays
        runnable on a single-node cluster.

        Two modes: scripted (`chaos_preempt_at` names the tick ordinal;
        `chaos_preempt_target` selects head/nonhead/any hostds — the
        deterministic way to preempt exactly one node of a multi-node
        cluster) or probabilistic (`chaos_preempt` per tick).
        """
        cfg = GLOBAL_CONFIG
        at = int(cfg.chaos_preempt_at)
        if at >= 0:
            target = str(cfg.chaos_preempt_target or "any")
            matches = (target == "any"
                       or (target == "head") == bool(is_head))
            with self._lock:
                n = self._next_index("preempt")
                if matches and n == at:
                    self._faults += 1
                    self.schedule.append(("preempt", n, "preempt"))
                    return True
            return False
        return self.should("preempt", cfg.chaos_preempt, "preempt")

    def stall_train_step(self) -> Optional[float]:
        """Chaos verdict for one session.report() step boundary: None
        (no fault) or seconds to stall BEFORE updating the progress
        beacon — so the stalled rank's beacon reads stale and the
        driver-side watchdog can classify it as the laggard.

        Same two modes as kill_worker: scripted
        (`chaos_stall_worker_salts` lists worker spawn ordinals; a
        listed worker stalls at its `chaos_stall_at`-th report) or
        probabilistic (`chaos_stall_worker` per report).
        """
        cfg = GLOBAL_CONFIG
        salts = str(cfg.chaos_stall_worker_salts or "")
        if salts and self.salt:
            listed = self.salt in [s.strip() for s in salts.split(",")]
            with self._lock:
                n = self._next_index("train")
                if listed and n == int(cfg.chaos_stall_at):
                    self._faults += 1
                    self.schedule.append(("train", n, "stall"))
                    return float(cfg.chaos_stall_s)
            return None
        if self.should("train", cfg.chaos_stall_worker, "stall"):
            return float(cfg.chaos_stall_s)
        return None

    def kill_ckpt_commit(self) -> bool:
        """Kill this process mid-checkpoint-save: the async writer draws
        this right before the COMMIT rename, when every shard file is on
        disk but the directory is still torn — the worst instant for a
        crash, and exactly what restore_latest() must survive.

        Same two modes as kill_worker: scripted (`chaos_ckpt_kill_salts`
        lists worker spawn ordinals; a listed worker dies at its
        `chaos_ckpt_kill_at`-th save — deterministic AND convergent,
        since the respawned worker carries a fresh ordinal) or
        probabilistic (`chaos_ckpt_kill` per save).
        """
        cfg = GLOBAL_CONFIG
        salts = str(cfg.chaos_ckpt_kill_salts or "")
        if salts and self.salt:
            listed = self.salt in [s.strip() for s in salts.split(",")]
            with self._lock:
                n = self._next_index("ckpt")
                if listed and n == int(cfg.chaos_ckpt_kill_at):
                    self._faults += 1
                    self.schedule.append(("ckpt", n, "kill"))
                    return True
            return False
        return self.should("ckpt", cfg.chaos_ckpt_kill, "kill")

    def kill_gcs(self) -> bool:
        """Kill the GCS process right before serving its next request.

        Scripted only: `chaos_kill_gcs_at` names the control-plane
        request ordinal at which this GCS incarnation os._exit(1)s, and
        `chaos_kill_gcs_salts` names which incarnations arm ('gcs0' is
        the first boot; the supervisor stamps respawns 'gcs1', 'gcs2',
        ...).  The default salts list arms only 'gcs0', so a supervised
        respawn replays the surviving schedule instead of dying at the
        same ordinal forever — multi-kill scenarios opt in by listing
        more incarnations (or '*').  Respects `chaos_max_faults` like
        the other scripted process kills.
        """
        cfg = GLOBAL_CONFIG
        at = int(cfg.chaos_kill_gcs_at)
        if at < 0:
            return False
        salts = str(cfg.chaos_kill_gcs_salts or "")
        listed = (salts.strip() == "*"
                  or (self.salt and self.salt in
                      [s.strip() for s in salts.split(",")]))
        with self._lock:
            n = self._next_index("gcs")
            if (listed and n == at
                    and not (self.max_faults
                             and self._faults >= self.max_faults)):
                self._faults += 1
                self.schedule.append(("gcs", n, "kill"))
                return True
        return False

    def kill_gcs_flush(self) -> bool:
        """Kill the GCS *inside* the N-th sqlite persistence flush —
        after the executemany, before the transaction commits.  The
        worst instant for the coalesced-write path from the batching PR:
        every row of the flush is staged but nothing is durable, so a
        restore must see the whole flush roll back (crash-atomicity)
        rather than a torn prefix.  Scripted via `chaos_kill_gcs_flush_at`
        with the same incarnation gating as kill_gcs.
        """
        cfg = GLOBAL_CONFIG
        at = int(cfg.chaos_kill_gcs_flush_at)
        if at < 0:
            return False
        salts = str(cfg.chaos_kill_gcs_salts or "")
        listed = (salts.strip() == "*"
                  or (self.salt and self.salt in
                      [s.strip() for s in salts.split(",")]))
        with self._lock:
            n = self._next_index("gcsflush")
            if (listed and n == at
                    and not (self.max_faults
                             and self._faults >= self.max_faults)):
                self._faults += 1
                self.schedule.append(("gcsflush", n, "kill"))
                return True
        return False

    # -- sustained link partitions ----------------------------------------

    def _parse_link_rules(self, spec: str) -> List[dict]:
        """Parse 'src>dst@start+duration[;...]' into rule dicts.

        Malformed entries are skipped (chaos config must never crash the
        runtime it is testing).  Rule state (window start, heal flag)
        lives on the dict — parsed once per spec string per process.
        """
        rules: List[dict] = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            try:
                src, rest = entry.split(">", 1)
                dst, rest = rest.split("@", 1)
                at, dur = rest.split("+", 1)
                rules.append({
                    "src": src.strip(), "dst": dst.strip(),
                    "at": int(at), "dur": float(dur),
                    "started": None, "healed": False,
                })
            except (ValueError, TypeError):
                continue
        return rules

    def _src_matches(self, src: str) -> bool:
        # "driver" names the saltless driver/launcher process so rules
        # can target it explicitly without "*" catching every daemon.
        return (src == "*" or src == self.salt
                or (src == "driver" and not self.salt))

    def link_fault(self, address: str) -> bool:
        """Verdict for one outbound send from this process to `address`:
        True = the link is blackholed right now, drop the send.

        Sustained, per-link, directional — unlike the probabilistic
        per-call drops.  Each rule opens a wall-clock window of
        `duration` seconds when this process's `start`-th call on that
        link occurs; the call ordinal only advances on links some rule
        names, so un-partitioned traffic pays one spec check.  Both the
        blackhole onset and the heal are flight-recorded on the "link"
        plane.
        """
        spec = str(GLOBAL_CONFIG.chaos_partition_links or "")
        if not spec:
            return False
        with self._lock:
            if spec != self._link_spec:
                self._link_spec = spec
                self._link_rules = self._parse_link_rules(spec)
            label = "gcs" if (_gcs_address and address == _gcs_address) \
                else address
            mine = [r for r in self._link_rules
                    if self._src_matches(r["src"])
                    and r["dst"] in ("*", label, address)]
            if not mine:
                return False
            n = self._next_index(f"link|{label}")
            now = time.monotonic()
            active = False
            fired, healed = [], []
            for r in mine:
                if (r["started"] is None and n == r["at"]
                        and not (self.max_faults
                                 and self._faults >= self.max_faults)):
                    r["started"] = now
                    self._faults += 1
                    self.schedule.append((f"link|{label}", n, "blackhole"))
                    fired.append(r)
                if r["started"] is not None and not r["healed"]:
                    if now - r["started"] < r["dur"]:
                        active = True
                    else:
                        r["healed"] = True
                        healed.append(r)
        # Record outside the lock: events.record takes its own locks.
        from ray_tpu.util import events
        for r in fired:
            events.record("link", "blackhole", src=r["src"], dst=label,
                          ordinal=n, duration_s=r["dur"])
        for r in healed:
            events.record("link", "heal", src=r["src"], dst=label,
                          after_s=r["dur"])
        return active


_chaos: Optional[ChaosController] = None
_chaos_lock = threading.Lock()


def get_chaos() -> Optional[ChaosController]:
    """The process-wide controller, or None when chaos is disabled.

    Hot paths call this on every interposed event; the disabled case is
    one cached config-attribute read.
    """
    if not GLOBAL_CONFIG.chaos_enabled:
        return None
    global _chaos
    if _chaos is None:
        with _chaos_lock:
            if _chaos is None:
                _chaos = ChaosController(
                    GLOBAL_CONFIG.chaos_seed, GLOBAL_CONFIG.chaos_max_faults)
    return _chaos


def reset() -> None:
    """Drop the process controller (tests flip seeds/flags between
    scenarios; the next `get_chaos()` rebuilds from current config)."""
    global _chaos
    with _chaos_lock:
        _chaos = None
