"""Function/class distribution via the GCS KV store.

Reference parity: python/ray/_private/function_manager.py — functions and
actor classes are cloudpickled once, exported to the GCS KV under a content
hash, and imported lazily (with caching) by workers.
"""

from __future__ import annotations

import hashlib
import threading

import cloudpickle


class FunctionManager:
    def __init__(self, kv_call):
        """kv_call: async fn(method, request) -> reply (bound to GCS Kv svc)."""
        self._kv_call = kv_call
        self._export_cache: dict[int, str] = {}
        self._import_cache: dict[str, object] = {}
        self._lock = threading.Lock()

    def export_cached(self, obj) -> str | None:
        """Synchronous cache peek — the submit fast path avoids an event-
        loop round trip when the function was already exported."""
        with self._lock:
            return self._export_cache.get(id(obj))

    async def export(self, job_id: int, obj) -> str:
        with self._lock:
            key = self._export_cache.get(id(obj))
            if key is not None:
                return key
        blob = cloudpickle.dumps(obj, protocol=5)
        key = f"fn:{job_id}:{hashlib.sha1(blob).hexdigest()}"
        # Typed contract (pb.KvPutRequest) — the function-distribution
        # path is the first library RPC migrated off pickled dicts.
        from ray_tpu import protocol
        await self._kv_call("kv_put", protocol.pb.KvPutRequest(
            ns="fn", key=key, value=blob, overwrite=False))
        with self._lock:
            self._export_cache[id(obj)] = key
            self._import_cache[key] = obj  # local fast path
        return key

    def fetch_cached(self, key: str):
        """Synchronous cache peek — the execution hot path avoids an
        event-loop round trip for already-imported functions."""
        with self._lock:
            return self._import_cache.get(key)

    async def fetch(self, key: str):
        with self._lock:
            if key in self._import_cache:
                return self._import_cache[key]
        from ray_tpu import protocol
        reply = await self._kv_call(
            "kv_get", protocol.pb.KvGetRequest(ns="fn", key=key))
        blob = reply.value if reply.found else None
        if blob is None:
            raise RuntimeError(f"function {key} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._import_cache[key] = obj
        return obj
