"""Worker process entrypoint
(reference: python/ray/_private/workers/default_worker.py).

Connects to the node's shm store + GCS, reports readiness to its hostd, and
blocks in the task execution loop.  Exits if its hostd disappears (orphan
protection, reference: raylet death → worker suicide).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--hostd", required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--job-id", type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOGLEVEL", "INFO"))
    boot_trace = os.environ.get("RAY_TPU_BOOT_TRACE")
    from ray_tpu._private.profiling import start_periodic_profile
    pr = start_periodic_profile("RAY_TPU_BOOT_PROFILE", "boot")
    t0 = time.perf_counter()

    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import JobID, NodeID
    from ray_tpu._private.rpc import RpcClient
    from ray_tpu.util import spans
    t_imports = time.perf_counter() - t0
    # Boot span: CoreWorker construction through WorkerReady ack, so a
    # creation storm shows up as a wall of long proc/boot spans (import
    # cost rides along in the payload — it predates the recorder).
    tok_boot = spans.begin("proc", "boot", pid=os.getpid(),
                           imports_ms=round(t_imports * 1e3, 1))

    cw = CoreWorker(
        mode="worker",
        gcs_address=args.gcs,
        store_path=args.store,
        node_id=NodeID.from_hex(args.node_id),
        hostd_address=args.hostd,
        job_id=JobID(args.job_id.to_bytes(4, "little")),
    )
    t_core = time.perf_counter() - t0

    # Tasks call ray_tpu.get/put/remote through the process-global worker.
    from ray_tpu import api
    api._worker = cw

    renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if renv_json:
        import json

        from ray_tpu._private import runtime_env as renv
        cache_root = os.environ.get(
            "RAY_TPU_RUNTIME_ENV_CACHE", "/tmp/ray_tpu/runtime_env")
        os.makedirs(cache_root, exist_ok=True)
        cw.io.run(renv.setup_in_worker(json.loads(renv_json), cw._kv_call,
                                       cache_root), timeout=120)

    hostd = RpcClient(args.hostd)
    # Registration retries: during a creation storm (hundreds of workers
    # booting on few cores) the daemon can miss a 10s window; a worker
    # dying here amplifies the storm instead of riding it out.
    last = None
    for attempt in range(4):
        try:
            cw.io.run(hostd.call("NodeManager", "WorkerReady", {
                "pid": os.getpid(),
                "worker_id": cw.worker_id,
                "address": cw.address,
                # Piggybacked so leases/actor records carry the native
                # route — peers skip the per-worker NativePort RPC.
                "native_port": (cw._native_rx.port
                                if cw._native_rx else 0),
            }, timeout=10 * (attempt + 1)))
            break
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5 * (attempt + 1))
    else:
        raise RuntimeError(f"WorkerReady never acknowledged: {last}")
    spans.end(tok_boot)
    if boot_trace:
        print(f"[boot-trace] imports={t_imports*1e3:.1f}ms "
              f"core_worker={(t_core - t_imports)*1e3:.1f}ms "
              f"ready_rpc={(time.perf_counter() - t0 - t_core)*1e3:.1f}ms "
              f"total={(time.perf_counter() - t0)*1e3:.1f}ms",
              file=sys.stderr, flush=True)
    if pr is not None:
        pr.disable()
        pr.dump_stats(os.path.join(
            os.environ["RAY_TPU_BOOT_PROFILE"], f"boot-{os.getpid()}.prof"))

    parent = os.getppid()

    def orphan_watch():
        while True:
            if os.getppid() != parent:
                logging.warning("hostd died; worker exiting")
                os._exit(1)
            time.sleep(1.0)

    threading.Thread(target=orphan_watch, daemon=True).start()

    # Graceful SIGTERM (reference: default_worker.py sigterm handler →
    # CoreWorkerProcess graceful exit).  Without this the worker dies
    # mid-task and the owner misreads a deliberate kill as a crash and
    # retries; here the handler reports the deliberate exit to hostd,
    # lets the in-flight task drain within worker_sigterm_grace_s, then
    # exits.  Hostd's _escalate_kill SIGKILLs anything that overstays.
    import signal

    from ray_tpu._private.config import GLOBAL_CONFIG

    from ray_tpu.util import events

    def _graceful_exit(signum=None, frame=None):
        # Black box first: the flight-recorder ring is the only record of
        # this worker's decisions once the process is gone.
        events.record("proc", "sigterm")
        events.dump_crash("sigterm")

        def drain():
            try:
                cw.io.run(hostd.call(
                    "NodeManager", "WorkerExiting",
                    {"pid": os.getpid(), "reason": "sigterm"}, timeout=2))
            except Exception:
                pass
            deadline = (time.monotonic()
                        + GLOBAL_CONFIG.worker_sigterm_grace_s)
            while cw._running_tasks and time.monotonic() < deadline:
                time.sleep(0.02)
            os._exit(0 if not cw._running_tasks else 1)
        # Drain on a thread: the signal may land on a frame holding locks
        # the in-flight task needs to finish.
        threading.Thread(target=drain, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _graceful_exit)
    except (ValueError, OSError):
        pass  # non-main-thread entry (tests importing main())

    # Fatal-error black box: an uncaught exception on any thread dumps
    # the ring before the default traceback handling runs.
    _prev_hook = sys.excepthook

    def _fatal_hook(tp, val, tb):
        events.record("proc", "fatal_error", error=repr(val))
        events.dump_crash("fatal_error")
        _prev_hook(tp, val, tb)

    sys.excepthook = _fatal_hook

    cw.run_task_loop()
    os._exit(0)


if __name__ == "__main__":
    main()
