"""Flagship model: decoder-only transformer (GPT family), TPU-first.

Design (no reference counterpart — Ray hosts models, it doesn't ship them;
this repo's north star BASELINE.md requires a GPT-2-125M fine-tune and a 7B
config):
  * pure functional: params are a pytree, forward is a jittable function —
    plays directly with pjit/GSPMD and donation;
  * layers are STACKED on a leading dim and applied with `lax.scan` — one
    compiled block regardless of depth (fast compiles, small HLO);
  * every param leaf has a logical sharding spec (parallel.sharding rules
    decide DP/FSDP/TP placement);
  * attention = flash (Pallas) on one chip, ring attention when the mesh has
    a seq axis > 1;
  * optional Switch-style MoE MLP for expert parallelism;
  * `jax.checkpoint` (remat) on the block when configured — trades FLOPs for
    HBM, the standard TPU memory lever.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.mesh import mesh_axis_size
from ray_tpu.parallel.sharding import (
    logical_to_spec, named_sharding, tree_shardings, with_logical_constraint)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 vocab padded to a multiple of 128
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16        # activation dtype (params kept fp32)
    n_experts: int = 0               # 0 = dense MLP; >0 = Switch MoE
    capacity_factor: float = 1.25
    remat: bool = False
    tie_embeddings: bool = True
    # lax.scan unroll factor over layers.  Unrolling lets XLA fuse and
    # schedule across layer boundaries (measured +33% on one chip, PERF.md)
    # at the cost of compile time; keep 1 for very deep/remat configs.
    scan_unroll: int = 1

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# Preset configs (BASELINE.md targets).
CONFIGS = {
    "nano": GPTConfig(vocab_size=512, n_layers=2, d_model=64, n_heads=4,
                      d_ff=128, max_seq_len=128, dtype=jnp.float32),
    "nano-moe": GPTConfig(vocab_size=512, n_layers=2, d_model=64, n_heads=4,
                          d_ff=128, max_seq_len=128, n_experts=4,
                          dtype=jnp.float32),
    "gpt2-small": GPTConfig(scan_unroll=12),       # 124M
    "gpt2-medium": GPTConfig(n_layers=24, d_model=1024, n_heads=16,
                             d_ff=4096, scan_unroll=8),
    "gpt2-xl": GPTConfig(n_layers=48, d_model=1600, n_heads=25, d_ff=6400,
                         scan_unroll=4),
    "7b": GPTConfig(vocab_size=32000, n_layers=32, d_model=4096, n_heads=32,
                    d_ff=11008, max_seq_len=4096, remat=True),
}


def param_specs(config: GPTConfig) -> dict:
    """Logical sharding spec tree, congruent with init_params output."""
    blocks = {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "kv"),
        "wk": ("layers", "embed", "heads", "kv"),
        "wv": ("layers", "embed", "heads", "kv"),
        "wo": ("layers", "heads", "kv", "embed"),
        "ln2_scale": ("layers", "embed"),
        "ln2_bias": ("layers", "embed"),
    }
    if config.n_experts:
        blocks.update({
            "router": ("layers", "embed", "experts"),
            "w_up": ("layers", "experts", "embed", "expert_mlp"),
            "w_down": ("layers", "experts", "expert_mlp", "embed"),
        })
    else:
        blocks.update({
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    specs = {
        # Table embed dims stay unsharded (vocab carries tensor+fsdp, see
        # parallel/sharding.py DEFAULT_RULES["vocab"]); pos_embed is tiny
        # and replicated.
        "tok_embed": ("vocab", None),
        "pos_embed": (None, None),
        "blocks": blocks,
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }
    if not config.tie_embeddings:
        specs["lm_head"] = ("embed", "vocab")
    return specs


def init_params(config: GPTConfig, key: jax.Array) -> dict:
    c = config
    n, d, h, dh, f = c.n_layers, c.d_model, c.n_heads, c.head_dim, c.d_ff
    keys = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / np.sqrt(fan_in))

    blocks = {
        "ln1_scale": jnp.ones((n, d)),
        "ln1_bias": jnp.zeros((n, d)),
        "wq": dense(next(keys), (n, d, h, dh), d),
        "wk": dense(next(keys), (n, d, h, dh), d),
        "wv": dense(next(keys), (n, d, h, dh), d),
        # Residual-branch outputs scaled per GPT-2 (1/sqrt(2*n_layers)).
        "wo": dense(next(keys), (n, h, dh, d), h * dh) / np.sqrt(2 * n),
        "ln2_scale": jnp.ones((n, d)),
        "ln2_bias": jnp.zeros((n, d)),
    }
    if c.n_experts:
        e = c.n_experts
        blocks["router"] = dense(next(keys), (n, d, e), d)
        blocks["w_up"] = dense(next(keys), (n, e, d, f), d)
        blocks["w_down"] = dense(next(keys), (n, e, f, d), f) / np.sqrt(2 * n)
    else:
        blocks["w_up"] = dense(next(keys), (n, d, f), d)
        blocks["w_down"] = dense(next(keys), (n, f, d), f) / np.sqrt(2 * n)

    params = {
        "tok_embed": jax.random.normal(next(keys), (c.vocab_size, d)) * 0.02,
        "pos_embed": jax.random.normal(next(keys), (c.max_seq_len, d)) * 0.01,
        "blocks": blocks,
        "final_ln_scale": jnp.ones((d,)),
        "final_ln_bias": jnp.zeros((d,)),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, c.vocab_size), d)
    return params


def shard_params(params: dict, mesh, config: GPTConfig, rules=None) -> dict:
    return jax.device_put(params,
                          tree_shardings(mesh, param_specs(config), rules))


def num_params(config: GPTConfig) -> int:
    shapes = jax.eval_shape(partial(init_params, config), jax.random.key(0))
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes))


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _moe_mlp(x, router, w_up, w_down, config: GPTConfig, mesh):
    """Switch-style top-1 MoE with dense dispatch (einsum one-hot masks —
    static shapes, XLA-friendly; no sort/scatter)."""
    b, l, d = x.shape
    e = config.n_experts
    t = b * l
    cap = int(math.ceil(t / e * config.capacity_factor))
    xt = x.reshape(t, d)

    logits = (xt.astype(jnp.float32) @ router.astype(jnp.float32))  # [T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, -1)                      # [T]
    expert = jnp.argmax(probs, -1)                 # [T]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)       # [T,E]
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0             # [T,E]
    keep = (pos < cap) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap) * keep[..., None]
    dispatch = pos_oh                                            # [T,E,C]

    ex_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    ex_in = with_logical_constraint(ex_in, ("experts", None, "embed"),
                                    mesh=mesh)
    hidden = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", ex_in,
                                    w_up.astype(x.dtype)))
    ex_out = jnp.einsum("ecf,efd->ecd", hidden, w_down.astype(x.dtype))
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ex_out)

    # Load-balancing aux loss (Switch eq. 4): mean prob * mean assignment.
    density = jnp.mean(onehot, 0)
    density_prob = jnp.mean(probs, 0)
    aux = e * jnp.sum(density * density_prob)
    return out.reshape(b, l, d), aux


def _block(x, p, config: GPTConfig, mesh):
    c = config
    h = _layernorm(x, p["ln1_scale"], p["ln1_bias"])
    q = jnp.einsum("bld,dhk->blhk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bld,dhk->blhk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bld,dhk->blhk", h, p["wv"].astype(h.dtype))
    q = with_logical_constraint(q, ("batch", "length", "heads", "kv"),
                                mesh=mesh)
    if mesh is not None and mesh_axis_size(mesh, "seq") > 1:
        attn = ring_attention(q, k, v, mesh=mesh, causal=True)
    else:
        attn = flash_attention(q, k, v, causal=True)
    attn_out = jnp.einsum("blhk,hkd->bld", attn, p["wo"].astype(h.dtype))
    x = x + attn_out

    h = _layernorm(x, p["ln2_scale"], p["ln2_bias"])
    if c.n_experts:
        mlp_out, aux = _moe_mlp(h, p["router"], p["w_up"], p["w_down"], c,
                                mesh)
    else:
        hidden = jax.nn.gelu(
            jnp.einsum("bld,df->blf", h, p["w_up"].astype(h.dtype)))
        hidden = with_logical_constraint(hidden, ("batch", "length", "mlp"),
                                         mesh=mesh)
        mlp_out = jnp.einsum("blf,fd->bld", hidden,
                             p["w_down"].astype(h.dtype))
        aux = jnp.zeros((), jnp.float32)
    x = x + mlp_out
    x = with_logical_constraint(x, ("batch", "length", "act_embed"), mesh=mesh)
    return x, aux


def forward(params: dict, tokens: jax.Array, config: GPTConfig,
            mesh=None, position_offset: int = 0) -> tuple[jax.Array,
                                                          jax.Array]:
    """tokens [B, L] int32 -> (logits [B, L, V], moe_aux_loss scalar)."""
    c = config
    x, aux = forward_trunk(params, tokens, c, mesh, position_offset)
    logits = lm_head(params, x, c)
    logits = with_logical_constraint(logits, ("batch", "length", "vocab"),
                                     mesh=mesh)
    return logits, aux


def lm_head(params: dict, x: jax.Array, config: GPTConfig) -> jax.Array:
    """Project hidden states [..., D] to vocab logits [..., V]."""
    head = (params["tok_embed"].T if config.tie_embeddings
            else params["lm_head"]).astype(config.dtype)
    return x @ head


def forward_trunk(params: dict, tokens: jax.Array, config: GPTConfig,
                  mesh=None, position_offset: int = 0) -> tuple[jax.Array,
                                                                jax.Array]:
    """Transformer stack up to (excluding) the lm head.
    tokens [B, L] -> (x [B, L, D], moe_aux_loss).

    position_offset shifts the learned position table: a suffix call at
    absolute position p must read pos_embed[p:p+l], not pos_embed[:l]
    (the cached decode path depends on this)."""
    c = config
    b, l = tokens.shape
    x = params["tok_embed"][tokens].astype(c.dtype)
    pos = jax.lax.dynamic_slice_in_dim(params["pos_embed"],
                                       position_offset, l)
    x = x + pos[None].astype(c.dtype)
    x = with_logical_constraint(x, ("batch", "length", "act_embed"), mesh=mesh)

    block = partial(_block, config=c, mesh=mesh)
    if c.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def body(x, layer_params):
        x, aux = block(x, layer_params)
        return x, aux

    x, auxes = jax.lax.scan(body, x, params["blocks"],
                            unroll=min(c.scan_unroll, c.n_layers))
    x = _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
    return x, jnp.sum(auxes)


def _block_cached(x, p, k_pool, v_pool, config: GPTConfig, block_tables,
                  positions, valid, ctx_lens):
    """One transformer block over a paged KV cache: new K/V are scattered
    into this layer's pool slice, then attention runs over the block
    table (ops/attention.py paged path).  x [B, T, D]; positions [B, T]
    absolute; ctx_lens [B] = context length including this slice."""
    from ray_tpu.ops.attention import paged_attention, paged_kv_update

    h = _layernorm(x, p["ln1_scale"], p["ln1_bias"])
    q = jnp.einsum("bld,dhk->blhk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bld,dhk->blhk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bld,dhk->blhk", h, p["wv"].astype(h.dtype))
    k_pool, v_pool = paged_kv_update(k_pool, v_pool, k, v, block_tables,
                                     positions, valid)
    attn = paged_attention(q, k_pool, v_pool, block_tables, ctx_lens,
                           positions)
    x = x + jnp.einsum("blhk,hkd->bld", attn, p["wo"].astype(h.dtype))

    h = _layernorm(x, p["ln2_scale"], p["ln2_bias"])
    hidden = jax.nn.gelu(
        jnp.einsum("bld,df->blf", h, p["w_up"].astype(h.dtype)))
    x = x + jnp.einsum("blf,fd->bld", hidden, p["w_down"].astype(h.dtype))
    return x, k_pool, v_pool


def forward_cached(params: dict, tokens: jax.Array, positions: jax.Array,
                   valid: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                   block_tables: jax.Array, ctx_lens: jax.Array,
                   config: GPTConfig):
    """Cached (incremental) trunk for autoregressive decode/prefill.

    tokens [B, T] is a SLICE of each lane's sequence at absolute
    `positions` [B, T] (per-lane offsets — lanes decode at different
    depths); K/V for the slice are written into the paged pools
    [n_layers, NB, BS, H, D] and attention covers each lane's whole
    block table.  `valid` masks padding lanes/overhang (their cache
    writes are dropped).  Returns (x [B, T, D], k_pool, v_pool) — the
    lm head is applied by the caller on the positions it needs, so a
    prefill chunk never materializes [B, T, V].

    Dense-MLP configs only (n_experts == 0): MoE decode would need
    per-token expert dispatch, which the serving engine doesn't support.
    """
    c = config
    if c.n_experts:
        raise NotImplementedError("cached decode supports dense MLP only")
    pos = jnp.clip(positions, 0, c.max_seq_len - 1)
    x = params["tok_embed"][tokens].astype(c.dtype)
    x = x + params["pos_embed"][pos].astype(c.dtype)

    def body(x, layer):
        p, k_l, v_l = layer
        x, k_l, v_l = _block_cached(x, p, k_l, v_l, c, block_tables,
                                    positions, valid, ctx_lens)
        return x, (k_l, v_l)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool),
        unroll=min(c.scan_unroll, c.n_layers))
    x = _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
    return x, k_pool, v_pool


def loss_fn(params: dict, batch: dict, config: GPTConfig, mesh=None):
    """batch = {"tokens": [B, L]} — next-token cross-entropy.

    Runs the model on the FULL length L and shifts targets instead of
    slicing inputs to L-1: the sequence dim must stay divisible by the
    mesh's seq axis for ring attention, and L-1 never is.

    Single chip uses the fused chunked cross-entropy (never materializes
    [B, L, V] — see ops/cross_entropy.py and PERF.md; the naive fp32
    log_softmax was ~75% of the train step).  Under a mesh the shard_map
    variant keeps the same property per-chip with vocab-sharded
    logsumexp; the naive path remains only as the fallback for
    non-divisible shapes.
    """
    from ray_tpu.ops.cross_entropy import (fused_cross_entropy,
                                           fused_cross_entropy_spmd,
                                           spmd_ce_applicable)

    tokens = batch["tokens"]
    c = config
    targets = jnp.roll(tokens, -1, axis=1)
    # Last position predicts the rolled-around token 0 — always masked.
    valid = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
    mask = batch.get("loss_mask")
    if mask is not None:
        valid = valid * mask

    multichip = mesh is not None and any(
        s > 1 for s in mesh.shape.values())
    if not multichip:
        x, aux = forward_trunk(params, tokens, c, mesh)
        b, l, d = x.shape
        head = (params["tok_embed"].T if c.tie_embeddings
                else params["lm_head"]).astype(c.dtype)
        loss = fused_cross_entropy(x.reshape(b * l, d), head,
                                   targets.reshape(-1), valid.reshape(-1))
        return loss + 0.01 * aux

    if spmd_ce_applicable(mesh, c.vocab_size, *tokens.shape):
        x, aux = forward_trunk(params, tokens, c, mesh)
        head = (params["tok_embed"].T if c.tie_embeddings
                else params["lm_head"]).astype(c.dtype)
        loss = fused_cross_entropy_spmd(x, head, targets, valid, mesh)
        return loss + 0.01 * aux

    logits, aux = forward(params, tokens, c, mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)
    return loss + 0.01 * aux


def make_train_step(config: GPTConfig, optimizer, mesh=None):
    """Returns (init_state, train_step) — the shared functional-LM
    contract (models/_functional.py): jittable train_step; under a mesh,
    params AND optimizer state are sharded (ZeRO-3: Adam moments inherit
    each param's sharding via GSPMD propagation through
    jit(optimizer.init)) and XLA inserts the collectives."""
    from ray_tpu.models._functional import make_train_step as _shared
    return _shared(config, optimizer, mesh, init_params=init_params,
                   loss_fn=loss_fn, param_specs=param_specs)
