"""Shared train-step factory for the functional LM families (gpt, llama).

One implementation of the (init_state, train_step) contract: under a mesh,
params AND optimizer state are sharded (ZeRO-3 via GSPMD propagation
through jit(optimizer.init)) and XLA inserts the collectives; train_step
is jittable with donation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_train_step(config, optimizer, mesh, *, init_params, loss_fn,
                    param_specs):
    """`init_params(config, key)`, `loss_fn(params, batch, config, mesh)`,
    `param_specs(config)` define the family; everything else is shared."""
    import optax

    def init_state(key):
        params = init_params(config, key)
        opt_state = optimizer.init(params)
        if mesh is not None:
            from ray_tpu.parallel.sharding import (
                shard_opt_state, tree_shardings)
            shardings = tree_shardings(mesh, param_specs(config))
            opt_state = shard_opt_state(opt_state, params, shardings, mesh)
            params = jax.device_put(params, shardings)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch, config, mesh)
        updates, opt_state = optimizer.update(grads, state["opt_state"],
                                              state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    return init_state, train_step
