"""ResNet image models (flax), TPU-first.

Design notes (no reference counterpart — Ray hosts models; BASELINE.md's
AIR end-to-end target is "Data preprocessing -> Train -> Serve, ResNet-50
ImageNet"):
  * GroupNorm instead of BatchNorm: stateless normalization keeps the
    train step a pure function of (params, batch) — no batch-stat sync
    collectives across data-parallel replicas and no mutable state to
    thread through pjit (the standard TPU recipe for functional training);
  * NHWC layout (XLA's native conv layout on TPU MXU);
  * data parallelism via the same logical-rules mesh as the transformers:
    batch splits over (data, fsdp), params replicated.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)   # resnet18
    num_classes: int = 10
    width: int = 64
    bottleneck: bool = False
    cifar_stem: bool = True    # 3x3/1 stem (32x32 inputs) vs 7x7/2+pool
    num_groups: int = 8        # GroupNorm groups
    dtype: Any = jnp.float32


CONFIGS = {
    "resnet18-cifar": ResNetConfig(),
    "resnet18": ResNetConfig(cifar_stem=False),
    "resnet50": ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                             cifar_stem=False, num_classes=1000,
                             dtype=jnp.bfloat16),
}


class _Block(nn.Module):
    filters: int
    strides: int
    bottleneck: bool
    num_groups: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        conv = lambda f, k, s: nn.Conv(f, (k, k), (s, s), padding="SAME",
                                       use_bias=False, dtype=self.dtype)
        norm = lambda: nn.GroupNorm(num_groups=self.num_groups,
                                    dtype=self.dtype)
        out_filters = self.filters * (4 if self.bottleneck else 1)
        residual = x
        if residual.shape[-1] != out_filters or self.strides != 1:
            residual = conv(out_filters, 1, self.strides)(x)
            residual = norm()(residual)
        if self.bottleneck:
            y = nn.relu(norm()(conv(self.filters, 1, 1)(x)))
            y = nn.relu(norm()(conv(self.filters, 3, self.strides)(y)))
            y = norm()(conv(out_filters, 1, 1)(y))
        else:
            y = nn.relu(norm()(conv(self.filters, 3, self.strides)(x)))
            y = norm()(conv(out_filters, 3, 1)(y))
        return nn.relu(y + residual)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        conv = lambda f, k, s: nn.Conv(f, (k, k), (s, s), padding="SAME",
                                       use_bias=False, dtype=c.dtype)
        x = x.astype(c.dtype)
        if c.cifar_stem:
            x = conv(c.width, 3, 1)(x)
        else:
            x = conv(c.width, 7, 2)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.GroupNorm(num_groups=c.num_groups, dtype=c.dtype)(x))
        for i, n_blocks in enumerate(c.stage_sizes):
            for j in range(n_blocks):
                x = _Block(filters=c.width * 2 ** i,
                           strides=2 if j == 0 and i > 0 else 1,
                           bottleneck=c.bottleneck,
                           num_groups=c.num_groups, dtype=c.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(c.num_classes, dtype=jnp.float32)(x)


def make_model(config: ResNetConfig, input_shape=(32, 32, 3)):
    """(init_params(rng), apply(params, images)) — images NHWC float."""
    model = ResNet(config=config)

    def init_params(rng):
        dummy = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
        return model.init(rng, dummy)

    return init_params, model.apply


def num_params(config: ResNetConfig, input_shape=(32, 32, 3)) -> int:
    init, _ = make_model(config, input_shape)
    shapes = jax.eval_shape(init, jax.random.key(0))
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes))


def make_train_step(config: ResNetConfig, optimizer, mesh=None,
                    input_shape=(32, 32, 3)):
    """(init_state, train_step): batch = {"images" [B,H,W,C], "labels" [B]}.
    Under a mesh, the batch is expected sharded over (data, fsdp) and
    params replicate; grads ride GSPMD's psum."""
    import optax

    from ray_tpu.parallel.sharding import with_logical_constraint

    init_p, apply = make_model(config, input_shape)

    def loss_fn(params, batch):
        images = batch["images"]
        if mesh is not None:
            images = with_logical_constraint(
                images, ("batch", None, None, None), mesh=mesh)
        logits = apply(params, images)
        labels = batch["labels"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        acc = jnp.mean((jnp.argmax(logits, -1) == labels)
                       .astype(jnp.float32))
        return nll.mean(), acc

    def init_state(key):
        params = init_p(key)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            params = jax.device_put(
                params, NamedSharding(mesh, PartitionSpec()))
        return {"params": params, "opt_state": optimizer.init(params),
                "step": jnp.zeros((), jnp.int32)}

    def train_step(state, batch):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], batch)
        updates, opt_state = optimizer.update(grads, state["opt_state"],
                                              state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss, "accuracy": acc})

    return init_state, train_step
