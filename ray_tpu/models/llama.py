"""Llama model family: RMSNorm + RoPE + SwiGLU + grouped-query attention.

Design follows models/gpt.py (no reference counterpart — Ray hosts models
rather than shipping them; BASELINE.md's north star names a Llama-2-7B
fine-tune):
  * pure functional params-pytree + jittable forward (pjit/GSPMD-ready);
  * layers stacked on a leading dim, applied with `lax.scan`;
  * every param leaf carries a logical sharding spec (parallel/sharding.py
    rules place DP/FSDP/TP; "kv_heads" shards GQA kv projections);
  * flash attention (Pallas) on one chip, ring attention over a seq axis;
  * rotary embeddings computed on the fly (no position table);
  * `jax.checkpoint` remat for the big configs.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.mesh import mesh_axis_size
from ray_tpu.parallel.sharding import (
    tree_shardings, with_logical_constraint)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layers: int = 32
    d_model: int = 4096
    n_heads: int = 32
    n_kv_heads: int = 32          # < n_heads = grouped-query attention
    d_ff: int = 11008             # SwiGLU hidden
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    scan_unroll: int = 1

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


CONFIGS = {
    "llama-tiny": LlamaConfig(vocab_size=512, n_layers=2, d_model=64,
                              n_heads=4, n_kv_heads=2, d_ff=128,
                              max_seq_len=128, dtype=jnp.float32),
    "llama-1b": LlamaConfig(vocab_size=32000, n_layers=22, d_model=2048,
                            n_heads=32, n_kv_heads=4, d_ff=5632,
                            max_seq_len=2048),
    "llama2-7b": LlamaConfig(remat=True),
    "llama3-8b": LlamaConfig(vocab_size=128256, n_layers=32, d_model=4096,
                             n_heads=32, n_kv_heads=8, d_ff=14336,
                             max_seq_len=8192, rope_theta=500000.0,
                             remat=True),
}


def param_specs(config: LlamaConfig) -> dict:
    blocks = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "kv"),
        "wk": ("layers", "embed", "kv_heads", "kv"),
        "wv": ("layers", "embed", "kv_heads", "kv"),
        "wo": ("layers", "heads", "kv", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    return {
        "tok_embed": ("vocab", None),
        "blocks": blocks,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    c = config
    n, d, h, kh, dh, f = (c.n_layers, c.d_model, c.n_heads, c.n_kv_heads,
                          c.head_dim, c.d_ff)
    keys = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)

    blocks = {
        "attn_norm": jnp.ones((n, d)),
        "wq": dense(next(keys), (n, d, h, dh), d),
        "wk": dense(next(keys), (n, d, kh, dh), d),
        "wv": dense(next(keys), (n, d, kh, dh), d),
        "wo": dense(next(keys), (n, h, dh, d), h * dh) / np.sqrt(2 * n),
        "mlp_norm": jnp.ones((n, d)),
        "w_gate": dense(next(keys), (n, d, f), d),
        "w_up": dense(next(keys), (n, d, f), d),
        "w_down": dense(next(keys), (n, f, d), f) / np.sqrt(2 * n),
    }
    return {
        "tok_embed": jax.random.normal(next(keys), (c.vocab_size, d)) * 0.02,
        "blocks": blocks,
        "final_norm": jnp.ones((d,)),
        "lm_head": dense(next(keys), (d, c.vocab_size), d),
    }


def shard_params(params: dict, mesh, config: LlamaConfig, rules=None) -> dict:
    return jax.device_put(params,
                          tree_shardings(mesh, param_specs(config), rules))


def num_params(config: LlamaConfig) -> int:
    shapes = jax.eval_shape(partial(init_params, config), jax.random.key(0))
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes))


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def _rope(x, theta: float, offset=0):
    """Rotary position embedding over [B, L, H, K] (rotate-half pairing:
    the head dim splits into two halves treated as (real, imag)).

    `offset` is the absolute position of x's first token: a scalar shared
    by the batch, or a per-lane [B] array (cached decode — lanes sit at
    different depths)."""
    b, l, h, k = x.shape
    half = k // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    off = jnp.asarray(offset, jnp.float32)
    pos = off[..., None] + jnp.arange(l, dtype=jnp.float32)  # [L] or [B, L]
    ang = pos[..., None] * freqs                      # [L, half] / [B, L, half]
    if ang.ndim == 2:
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _block(x, p, config: LlamaConfig, mesh, position_offset=0):
    c = config
    h = _rmsnorm(x, p["attn_norm"], c.norm_eps)
    q = jnp.einsum("bld,dhk->blhk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bld,dhk->blhk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bld,dhk->blhk", h, p["wv"].astype(h.dtype))
    q = _rope(q, c.rope_theta, position_offset)
    k = _rope(k, c.rope_theta, position_offset)
    if c.q_per_kv > 1:
        # GQA: each kv head serves q_per_kv query heads.  Materializing
        # the repeat keeps the attention kernels head-uniform; XLA fuses
        # the broadcast into the kernel operand load.
        k = jnp.repeat(k, c.q_per_kv, axis=2)
        v = jnp.repeat(v, c.q_per_kv, axis=2)
    q = with_logical_constraint(q, ("batch", "length", "heads", "kv"),
                                mesh=mesh)
    if mesh is not None and mesh_axis_size(mesh, "seq") > 1:
        attn = ring_attention(q, k, v, mesh=mesh, causal=True)
    else:
        attn = flash_attention(q, k, v, causal=True)
    x = x + jnp.einsum("blhk,hkd->bld", attn, p["wo"].astype(h.dtype))

    h = _rmsnorm(x, p["mlp_norm"], c.norm_eps)
    gate = jax.nn.silu(jnp.einsum("bld,df->blf", h,
                                  p["w_gate"].astype(h.dtype)))
    up = jnp.einsum("bld,df->blf", h, p["w_up"].astype(h.dtype))
    hidden = with_logical_constraint(gate * up, ("batch", "length", "mlp"),
                                     mesh=mesh)
    x = x + jnp.einsum("blf,fd->bld", hidden, p["w_down"].astype(h.dtype))
    return with_logical_constraint(x, ("batch", "length", "act_embed"),
                                   mesh=mesh)


def forward_trunk(params: dict, tokens: jax.Array, config: LlamaConfig,
                  mesh=None, position_offset=0) -> jax.Array:
    """tokens [B, L] -> hidden states [B, L, D] (pre-head, normed).

    position_offset rotates RoPE as if tokens started at that absolute
    position (scalar or per-lane [B]) — single-token decode steps depend
    on this; without it every suffix call re-rotates from position 0."""
    c = config
    x = params["tok_embed"][tokens].astype(c.dtype)
    x = with_logical_constraint(x, ("batch", "length", "act_embed"),
                                mesh=mesh)
    block = partial(_block, config=c, mesh=mesh,
                    position_offset=position_offset)
    if c.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def body(x, layer_params):
        return block(x, layer_params), None

    x, _ = jax.lax.scan(body, x, params["blocks"],
                        unroll=min(c.scan_unroll, c.n_layers))
    return _rmsnorm(x, params["final_norm"], c.norm_eps)


def forward(params: dict, tokens: jax.Array, config: LlamaConfig,
            mesh=None, position_offset=0) -> jax.Array:
    """tokens [B, L] -> logits [B, L, V]."""
    x = forward_trunk(params, tokens, config, mesh, position_offset)
    logits = jnp.einsum("bld,dv->blv", x,
                        params["lm_head"].astype(config.dtype))
    return with_logical_constraint(logits, ("batch", "length", "vocab"),
                                   mesh=mesh)


def lm_head(params: dict, x: jax.Array, config: LlamaConfig) -> jax.Array:
    """Project hidden states [..., D] to vocab logits [..., V]."""
    return x @ params["lm_head"].astype(config.dtype)


def _block_cached(x, p, k_pool, v_pool, config: LlamaConfig, block_tables,
                  positions, valid, ctx_lens):
    """One Llama block over a paged KV cache.  K/V are cached with
    kv_heads (GQA un-repeated — the whole point of the grouped cache);
    the paged attention path expands groups itself."""
    from ray_tpu.ops.attention import paged_attention, paged_kv_update

    c = config
    h = _rmsnorm(x, p["attn_norm"], c.norm_eps)
    q = jnp.einsum("bld,dhk->blhk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bld,dhk->blhk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bld,dhk->blhk", h, p["wv"].astype(h.dtype))
    # Per-token rotation at each token's own absolute position: offset =
    # positions[:, 0] with L-consecutive slices means positions must be
    # contiguous per lane, which prefill/decode slices always are.
    q = _rope(q, c.rope_theta, positions[:, 0])
    k = _rope(k, c.rope_theta, positions[:, 0])
    k_pool, v_pool = paged_kv_update(k_pool, v_pool, k, v, block_tables,
                                     positions, valid)
    attn = paged_attention(q, k_pool, v_pool, block_tables, ctx_lens,
                           positions)
    x = x + jnp.einsum("blhk,hkd->bld", attn, p["wo"].astype(h.dtype))

    h = _rmsnorm(x, p["mlp_norm"], c.norm_eps)
    gate = jax.nn.silu(jnp.einsum("bld,df->blf", h,
                                  p["w_gate"].astype(h.dtype)))
    up = jnp.einsum("bld,df->blf", h, p["w_up"].astype(h.dtype))
    x = x + jnp.einsum("blf,fd->bld", gate * up,
                       p["w_down"].astype(h.dtype))
    return x, k_pool, v_pool


def forward_cached(params: dict, tokens: jax.Array, positions: jax.Array,
                   valid: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                   block_tables: jax.Array, ctx_lens: jax.Array,
                   config: LlamaConfig):
    """Cached (incremental) trunk — same contract as gpt.forward_cached:
    tokens [B, T] at per-lane absolute `positions`, paged pools
    [n_layers, NB, BS, KH, D] (KH = n_kv_heads), returns
    (x [B, T, D], k_pool, v_pool)."""
    c = config
    x = params["tok_embed"][tokens].astype(c.dtype)

    def body(x, layer):
        p, k_l, v_l = layer
        x, k_l, v_l = _block_cached(x, p, k_l, v_l, c, block_tables,
                                    positions, valid, ctx_lens)
        return x, (k_l, v_l)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], k_pool, v_pool),
        unroll=min(c.scan_unroll, c.n_layers))
    x = _rmsnorm(x, params["final_norm"], c.norm_eps)
    return x, k_pool, v_pool


def loss_fn(params: dict, batch: dict, config: LlamaConfig, mesh=None):
    """Next-token cross-entropy; same shift/mask scheme as gpt.loss_fn
    (full-length forward, rolled targets, last position masked).  Single
    chip rides the fused chunked cross-entropy; a mesh rides the
    shard_map variant (vocab-sharded logsumexp), with the naive path as
    the non-divisible-shape fallback."""
    from ray_tpu.ops.cross_entropy import (fused_cross_entropy,
                                           fused_cross_entropy_spmd,
                                           spmd_ce_applicable)

    c = config
    tokens = batch["tokens"]
    targets = jnp.roll(tokens, -1, axis=1)
    valid = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
    mask = batch.get("loss_mask")
    if mask is not None:
        valid = valid * mask

    multichip = mesh is not None and any(
        s > 1 for s in mesh.shape.values())
    if not multichip:
        x = forward_trunk(params, tokens, c, mesh)
        b, l, d = x.shape
        return fused_cross_entropy(
            x.reshape(b * l, d), params["lm_head"].astype(c.dtype),
            targets.reshape(-1), valid.reshape(-1))

    if spmd_ce_applicable(mesh, c.vocab_size, *tokens.shape):
        x = forward_trunk(params, tokens, c, mesh)
        return fused_cross_entropy_spmd(
            x, params["lm_head"].astype(c.dtype), targets, valid, mesh)

    logits = forward(params, tokens, c, mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)


def make_train_step(config: LlamaConfig, optimizer, mesh=None):
    """(init_state, train_step) — the shared functional-LM contract
    (models/_functional.py)."""
    from ray_tpu.models._functional import make_train_step as _shared
    return _shared(config, optimizer, mesh, init_params=init_params,
                   loss_fn=loss_fn, param_specs=param_specs)
