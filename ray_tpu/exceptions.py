"""Public exception hierarchy.

Mirrors the capability surface of the reference's python/ray/exceptions.py:
task errors wrap the remote traceback, actor errors carry actor identity,
and lost objects raise a reconstruction-aware error.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTpuTimeoutError(RayTpuError, TimeoutError):
    """A blocking get()/wait() exceeded its timeout."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    The remote traceback string is carried so the driver sees where the
    failure happened (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor failures."""


class ActorDiedError(ActorError):
    """The actor is dead: creation failed, it was killed, or it crashed
    beyond its max_restarts budget."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is restarting; the call may be retried."""


class ObjectLostError(RayTpuError):
    """An object was evicted/lost and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"object {object_id} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class ObjectStoreFullError(RayTpuError):
    """Allocation failed even after eviction/spilling."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a task/actor runtime environment failed."""


class PlacementGroupUnschedulableError(RayTpuError):
    """The placement group cannot fit in the cluster."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled with ray_tpu.cancel()."""


class GetTimeoutError(RayTpuTimeoutError):
    """Alias kept for API parity with the reference."""


__all__ = [
    "RayTpuError",
    "RayTpuTimeoutError",
    "TaskError",
    "WorkerCrashedError",
    "ActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ObjectLostError",
    "ObjectStoreFullError",
    "RuntimeEnvSetupError",
    "PlacementGroupUnschedulableError",
    "TaskCancelledError",
    "GetTimeoutError",
]
