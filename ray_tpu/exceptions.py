"""Public exception hierarchy.

Mirrors the capability surface of the reference's python/ray/exceptions.py:
task errors wrap the remote traceback, actor errors carry actor identity,
and lost objects raise a reconstruction-aware error.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTpuTimeoutError(RayTpuError, TimeoutError):
    """A blocking get()/wait() exceeded its timeout."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    The remote traceback string is carried so the driver sees where the
    failure happened (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor failures."""


class ActorDiedError(ActorError):
    """The actor is dead: creation failed, it was killed, or it crashed
    beyond its max_restarts budget."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is restarting; the call may be retried."""


class ServeOverloadedError(RayTpuError):
    """Every replica of a deployment is saturated AND its bounded
    admission queue is full: the request was shed instead of queued.

    Carries a ``retry_after_s`` hint so well-behaved clients back off
    instead of hammering an overloaded deployment (the serving-plane
    analogue of HTTP 503 + Retry-After)."""

    def __init__(self, deployment: str = "", retry_after_s: float = 1.0,
                 queued: int = 0, limit: int = 0):
        self.deployment = deployment
        self.retry_after_s = float(retry_after_s)
        self.queued = queued
        self.limit = limit
        super().__init__(
            f"deployment {deployment!r} overloaded: {queued} request(s) "
            f"already queued (limit {limit}); retry after "
            f"{self.retry_after_s:g}s")

    def __reduce__(self):
        return (type(self), (self.deployment, self.retry_after_s,
                             self.queued, self.limit))


class ReplicaStreamLostError(RayTpuError):
    """A serve replica no longer knows the requested stream id — it was
    restarted (losing all in-progress generators) between two chunk
    pulls.  The handle treats this exactly like replica death: heal and
    resubmit under the stream's failover policy."""

    def __init__(self, stream_id: int = 0):
        self.stream_id = stream_id
        super().__init__(
            f"stream {stream_id} lost: replica restarted mid-stream")

    def __reduce__(self):
        return (type(self), (self.stream_id,))


class ObjectLostError(RayTpuError):
    """An object was evicted/lost and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"object {object_id} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class ObjectStoreFullError(RayTpuError):
    """Allocation failed even after eviction/spilling."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a task/actor runtime environment failed."""


class PlacementGroupUnschedulableError(RayTpuError):
    """The placement group cannot fit in the cluster."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled with ray_tpu.cancel()."""


class GetTimeoutError(RayTpuTimeoutError):
    """Alias kept for API parity with the reference."""


__all__ = [
    "RayTpuError",
    "RayTpuTimeoutError",
    "TaskError",
    "WorkerCrashedError",
    "ActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ServeOverloadedError",
    "ReplicaStreamLostError",
    "ObjectLostError",
    "ObjectStoreFullError",
    "RuntimeEnvSetupError",
    "PlacementGroupUnschedulableError",
    "TaskCancelledError",
    "GetTimeoutError",
]
