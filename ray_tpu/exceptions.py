"""Public exception hierarchy.

Mirrors the capability surface of the reference's python/ray/exceptions.py:
task errors wrap the remote traceback, actor errors carry actor identity,
and lost objects raise a reconstruction-aware error.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTpuTimeoutError(RayTpuError, TimeoutError):
    """A blocking get()/wait() exceeded its timeout."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    The remote traceback string is carried so the driver sees where the
    failure happened (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str, self.cause))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor failures."""


class ActorDiedError(ActorError):
    """The actor is dead: creation failed, it was killed, or it crashed
    beyond its max_restarts budget."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is restarting; the call may be retried."""


class ServeOverloadedError(RayTpuError):
    """Every replica of a deployment is saturated AND its bounded
    admission queue is full: the request was shed instead of queued.

    Carries a ``retry_after_s`` hint so well-behaved clients back off
    instead of hammering an overloaded deployment (the serving-plane
    analogue of HTTP 503 + Retry-After)."""

    def __init__(self, deployment: str = "", retry_after_s: float = 1.0,
                 queued: int = 0, limit: int = 0):
        self.deployment = deployment
        self.retry_after_s = float(retry_after_s)
        self.queued = queued
        self.limit = limit
        super().__init__(
            f"deployment {deployment!r} overloaded: {queued} request(s) "
            f"already queued (limit {limit}); retry after "
            f"{self.retry_after_s:g}s")

    def __reduce__(self):
        return (type(self), (self.deployment, self.retry_after_s,
                             self.queued, self.limit))


class ReplicaStreamLostError(RayTpuError):
    """A serve replica no longer knows the requested stream id — it was
    restarted (losing all in-progress generators) between two chunk
    pulls.  The handle treats this exactly like replica death: heal and
    resubmit under the stream's failover policy."""

    def __init__(self, stream_id: int = 0):
        self.stream_id = stream_id
        super().__init__(
            f"stream {stream_id} lost: replica restarted mid-stream")

    def __reduce__(self):
        return (type(self), (self.stream_id,))


class TrainPreemptedError(RayTpuError):
    """A training worker aborted at a step boundary because its host
    received a preemption notice (TPU maintenance event / spot
    reclamation).  The session's preemption hook has already raced its
    proactive checkpoint save against the grace window, so an elastic
    restart resumes having lost at most the in-flight step.

    Preserved across the task-error boundary (core_worker keeps the
    type instead of wrapping it in TaskError) so the driver can route
    it to the preemption recovery path instead of the crash path."""

    def __init__(self, grace_s: float = 0.0, rank: int = -1):
        self.grace_s = float(grace_s)
        self.rank = rank
        super().__init__(
            f"training worker rank {rank} preempted (grace window "
            f"{self.grace_s:g}s): aborted at the step boundary after the "
            f"proactive checkpoint save")

    def __reduce__(self):
        return (type(self), (self.grace_s, self.rank))


class TrainHungError(RayTpuError):
    """The gang made no observable progress (no report consumed, no
    step beacon advanced) for longer than ``train_hang_timeout_s``.

    Carries the watchdog's diagnosis: which ranks lag the gang's
    furthest step, how stale each rank's last beacon is, and the live
    per-rank thread stacks collected through the hostd stack-collection
    RPC — a bounded, diagnosed failure instead of an infinite wait in a
    collective."""

    def __init__(self, timeout_s: float = 0.0, laggard_ranks=None,
                 beacon_ages=None, stacks: str = ""):
        self.timeout_s = float(timeout_s)
        self.laggard_ranks = list(laggard_ranks or [])
        self.beacon_ages = dict(beacon_ages or {})
        self.stacks = stacks
        ages = ", ".join(
            f"rank {r}: {self.beacon_ages.get(r, -1.0):.1f}s"
            for r in self.laggard_ranks)
        super().__init__(
            f"training gang hung: no progress for {self.timeout_s:g}s; "
            f"laggard rank(s) {self.laggard_ranks} "
            f"(last beacon age {ages or 'unknown'})"
            + (f"\n--- live worker stacks ---\n{stacks}" if stacks else ""))

    def __reduce__(self):
        return (type(self), (self.timeout_s, self.laggard_ranks,
                             self.beacon_ages, self.stacks))


class ObjectLostError(RayTpuError):
    """An object was evicted/lost and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"object {object_id} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))


class ObjectStoreFullError(RayTpuError):
    """Allocation failed even after eviction/spilling."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a task/actor runtime environment failed."""


class PlacementGroupUnschedulableError(RayTpuError):
    """The placement group cannot fit in the cluster."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled with ray_tpu.cancel()."""


class GetTimeoutError(RayTpuTimeoutError):
    """Alias kept for API parity with the reference."""


__all__ = [
    "RayTpuError",
    "RayTpuTimeoutError",
    "TaskError",
    "WorkerCrashedError",
    "ActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ServeOverloadedError",
    "ReplicaStreamLostError",
    "TrainPreemptedError",
    "TrainHungError",
    "ObjectLostError",
    "ObjectStoreFullError",
    "RuntimeEnvSetupError",
    "PlacementGroupUnschedulableError",
    "TaskCancelledError",
    "GetTimeoutError",
]
