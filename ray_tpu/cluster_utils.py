"""In-process multi-node cluster for tests and local experiments.

Reference parity: python/ray/cluster_utils.py:99 `class Cluster`
(add_node:165) — N full nodes (each its own hostd daemon + shm store +
worker pool) on one machine sharing one GCS; the workhorse for distributed
tests (failover, spillback, placement groups, reconstruction).
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu._private import node as node_mod


class Cluster:
    def __init__(self, initialize_head: bool = True, connect: bool = False,
                 head_node_args: Optional[dict] = None):
        self.session_dir = node_mod.new_session_dir()
        self.group = node_mod.ProcessGroup()
        self.gcs_address = node_mod.start_gcs(self.session_dir, self.group, watch_parent=True)
        self.nodes: list[dict] = []
        self._connected = False
        if initialize_head:
            self.add_node(head=True, **(head_node_args or {}))
        if connect:
            self.connect()

    @property
    def address(self) -> str:
        return self.gcs_address

    def add_node(self, *, num_cpus: float = 2, num_tpus: float | None = None,
                 resources: Optional[dict] = None,
                 object_store_memory: int = 64 << 20,
                 head: bool = False) -> dict:
        node = node_mod.start_hostd(
            self.gcs_address, self.session_dir, self.group,
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            store_capacity=object_store_memory, head=head)
        self.nodes.append(node)
        return node

    def remove_node(self, node: dict, allow_graceful: bool = False):
        """Kill a node's daemon (and with it, its workers).  Hard kill by
        default — this is the chaos path (reference: NodeKillerActor,
        test_utils.py:1337)."""
        proc = node["proc"]
        if allow_graceful:
            proc.terminate()
        else:
            proc.kill()
        proc.wait(timeout=10)
        if node in self.nodes:
            self.nodes.remove(node)
        if proc in self.group.procs:
            self.group.procs.remove(proc)

    def wait_for_nodes(self, timeout: float = 30):
        """Block until every added node is alive in the GCS view."""
        import asyncio

        from ray_tpu._private.rpc import RpcClient

        async def poll():
            gcs = RpcClient(self.gcs_address)
            try:
                deadline = time.monotonic() + timeout
                want = {n["node_id"] for n in self.nodes}
                while time.monotonic() < deadline:
                    reply = await gcs.call("Gcs", "get_nodes", {}, timeout=5)
                    alive = {n.node_id.hex() for n in reply["nodes"]
                             if n.alive}
                    if want <= alive:
                        return
                    await asyncio.sleep(0.1)
                raise TimeoutError(
                    f"nodes not alive after {timeout}s: {want - alive}")
            finally:
                await gcs.close()

        asyncio.run(poll())

    def connect(self):
        import ray_tpu
        ray_tpu.init(address=self.gcs_address)
        self._connected = True

    def shutdown(self):
        import ray_tpu
        if self._connected and ray_tpu.is_initialized():
            ray_tpu.shutdown()
            self._connected = False
        self.group.reap()
        self.nodes.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
