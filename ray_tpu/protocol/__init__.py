"""Typed proto contracts for the RPC layer.

Reference parity: src/ray/protobuf/ (node_manager.proto,
gcs_service.proto, common.proto) — the contracts a non-Python peer needs,
generated to Python via scripts/gen_proto.sh and checked in.

The transport (rpc.py) carries these with a proto payload marker: wire
bytes are `\\x03 | u8 name_len | message name | SerializeToString()`.
The registry below maps short names back to classes on receive.
"""

from ray_tpu.protocol import raytpu_pb2 as pb

REGISTRY = {
    cls.DESCRIPTOR.name: cls
    for cls in (
        pb.ResourcesP,
        pb.PullObjectMetaRequest, pb.PullObjectMetaReply,
        pb.PullObjectChunkRequest, pb.PullObjectChunkReply,
        pb.PushObjectRequest, pb.PushObjectReply,
        pb.HeartbeatRequest, pb.HeartbeatReply,
        # Task/lease/GCS control plane (incremental migration off pickled
        # dicts; reference: common.proto TaskSpec, node_manager.proto
        # RequestWorkerLease, gcs_service.proto KV):
        pb.TaskArgP, pb.InlineValueP, pb.TaskSpecP,
        pb.PushTaskRequest, pb.PushTaskReply, pb.ReturnValueP,
        pb.RequestWorkerLeaseRequest, pb.RequestWorkerLeaseReply,
        pb.ReturnWorkerRequest, pb.ReturnWorkerReply,
        pb.RegisterNodeRequest, pb.RegisterNodeReply,
        pb.KvPutRequest, pb.KvPutReply,
        pb.KvGetRequest, pb.KvGetReply,
        pb.KvDelRequest, pb.KvDelReply,
    )
}


def encode(msg) -> bytes:
    name = type(msg).DESCRIPTOR.name.encode()
    return bytes([len(name)]) + name + msg.SerializeToString()


def decode(data: bytes):
    n = data[0]
    name = data[1:1 + n].decode()
    cls = REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown proto message {name!r}")
    return cls.FromString(data[1 + n:])


def is_message(obj) -> bool:
    return hasattr(obj, "DESCRIPTOR") and hasattr(obj, "SerializeToString")
