"""TaskSpec <-> TaskSpecP conversion.

Reference parity: src/ray/common/task/task_spec.h wraps the TaskSpec
proto; python builds specs through TaskSpecBuilder.  Here the runtime's
internal dataclass (protocol.py TaskSpec) converts losslessly to the
typed wire message, which is what a non-Python submitter (C++ client,
future native daemons) speaks.  Inline values carry a codec tag: Python
peers write "pickle5"; a C++ producer can submit "raw" bytes args.
"""

from __future__ import annotations

import json

from ray_tpu.protocol import pb
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    PlacementGroupID,
    TaskID,
)
from ray_tpu._private.protocol import RefArg, Resources, TaskSpec, ValueArg


def _arg_to_proto(arg) -> pb.TaskArgP:
    p = pb.TaskArgP()
    if isinstance(arg, RefArg):
        p.id = arg.id_binary
        p.owner_address = arg.owner_address
    else:
        p.value.data = arg.data
        p.value.metadata = arg.metadata or b""
        p.value.codec = "pickle5"
    return p


def _arg_from_proto(p: pb.TaskArgP):
    if p.WhichOneof("arg") == "id":
        return RefArg(p.id, p.owner_address)
    return ValueArg(p.value.data, p.value.metadata)


def taskspec_to_proto(spec: TaskSpec) -> pb.TaskSpecP:
    m = pb.TaskSpecP(
        task_id=spec.task_id.binary(),
        job_id=spec.job_id.binary(),
        name=spec.name,
        fn_key=spec.fn_key,
        num_returns=spec.num_returns,
        max_retries=spec.max_retries,
        retry_exceptions=spec.retry_exceptions,
        owner_address=spec.owner_address,
        actor_id=spec.actor_id.binary() if spec.actor_id else b"",
        actor_creation=spec.actor_creation,
        method_name=spec.method_name,
        seq_no=spec.seq_no,
        max_concurrency=spec.max_concurrency,
        scheduling_strategy=spec.scheduling_strategy or "DEFAULT",
        placement_group_id=(spec.placement_group.binary()
                            if spec.placement_group else b""),
        bundle_index=spec.bundle_index,
        runtime_env_json=(json.dumps(spec.runtime_env, sort_keys=True)
                          if spec.runtime_env else ""),
        node_affinity=(spec.node_affinity.binary()
                       if spec.node_affinity else b""),
        node_affinity_soft=spec.node_affinity_soft,
    )
    for k, v in spec.resources.to_dict().items():
        m.resources.amounts[k] = v
    for a in spec.args:
        m.args.append(_arg_to_proto(a))
    for k, v in spec.kwargs.items():
        m.kwargs[k].CopyFrom(_arg_to_proto(v))
    return m


def taskspec_from_proto(m: pb.TaskSpecP) -> TaskSpec:
    amounts = dict(m.resources.amounts)
    res = Resources(
        cpu=amounts.pop("CPU", 0.0),
        tpu=amounts.pop("TPU", 0.0),
        memory=amounts.pop("memory", 0.0),
        custom=amounts,
    )
    spec = TaskSpec(
        task_id=TaskID(m.task_id),
        job_id=JobID(m.job_id),
        name=m.name,
        fn_key=m.fn_key,
        args=[_arg_from_proto(a) for a in m.args],
        kwargs={k: _arg_from_proto(v) for k, v in m.kwargs.items()},
        num_returns=m.num_returns or 1,
        resources=res,
        max_retries=m.max_retries,
        retry_exceptions=m.retry_exceptions,
        owner_address=m.owner_address,
        actor_id=ActorID(m.actor_id) if m.actor_id else None,
        actor_creation=m.actor_creation,
        method_name=m.method_name,
        max_concurrency=m.max_concurrency,
        placement_group=(PlacementGroupID(m.placement_group_id)
                         if m.placement_group_id else None),
        bundle_index=m.bundle_index,
        scheduling_strategy=m.scheduling_strategy or "DEFAULT",
        runtime_env=(json.loads(m.runtime_env_json)
                     if m.runtime_env_json else {}),
        node_affinity=(NodeID(m.node_affinity)
                       if m.node_affinity else None),
        node_affinity_soft=m.node_affinity_soft,
    )
    spec.seq_no = m.seq_no
    return spec
