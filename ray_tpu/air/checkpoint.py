"""Checkpoint: dict <-> directory interconvertible training state.

Reference parity: python/ray/air/checkpoint.py:63 (Checkpoint with
from_dict/to_dict/from_directory/to_directory/uri forms).  TPU idiom: the
dict form holds host numpy pytrees (device arrays are fetched before
checkpointing — orbax-style async device-to-host saving hooks in later).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Optional

_DICT_FILE = "checkpoint.pkl"
_FILES_KEY = "_checkpoint_files"   # dict key holding packed directory files


class Checkpoint:
    def __init__(self, data: Optional[dict] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data/directory required")
        self._data = data
        self._dir = directory

    # -------- constructors --------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    # -------- accessors --------

    def to_dict(self) -> dict:
        """Dict form.  A directory checkpoint made from arbitrary files
        (e.g. orbax output) round-trips: every file is packed under the
        reserved _FILES_KEY (reference: air/checkpoint.py dict<->dir packs
        the full directory, _checkpoint.py _pack)."""
        if self._data is not None:
            return dict(self._data)
        pkl = os.path.join(self._dir, _DICT_FILE)
        data: dict = {}
        if os.path.isfile(pkl):
            with open(pkl, "rb") as f:
                data = pickle.load(f)
        files: dict = {}
        for root, _, names in os.walk(self._dir):
            for name in names:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, self._dir)
                if rel == _DICT_FILE:
                    continue
                with open(full, "rb") as f:
                    files[rel] = f.read()
        if files:
            data = dict(data)
            data[_FILES_KEY] = files
        return data

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(), "ray_tpu_ckpt",
                                uuid.uuid4().hex[:12])
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            data = dict(self._data)
            files = dict(data.pop(_FILES_KEY, {}))
            if data or not files:
                buf = pickle.dumps(data)
                files[_DICT_FILE] = buf
            # Per-FILE atomic replace (an os.replace of a directory onto an
            # existing non-empty directory raises ENOTEMPTY).
            for rel, blob in files.items():
                dest = os.path.join(path, rel)
                parent = os.path.dirname(dest)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                tmpf = dest + ".tmp"
                with open(tmpf, "wb") as f:
                    f.write(blob)
                os.replace(tmpf, dest)
        return path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir={self._dir}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Ship as dict form so checkpoints survive crossing process
        # boundaries even when the directory is node-local.
        return (Checkpoint.from_dict, (self.to_dict(),))
