"""Checkpoint: dict <-> directory interconvertible training state.

Reference parity: python/ray/air/checkpoint.py:63 (Checkpoint with
from_dict/to_dict/from_directory/to_directory/uri forms).  TPU idiom: the
dict form holds host numpy pytrees; sharded directories written by
`ray_tpu.checkpoint` (orbax-style async device-to-host saving) interop
losslessly via `from_sharded_dir`/`to_pytree`.

Temporary directories minted by `to_directory(path=None)` are tracked in
a module registry: `Checkpoint.delete()` reclaims one checkpoint's
disk, `cleanup_tmp()` sweeps everything this process created.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
import uuid
from typing import Any, Optional

_DICT_FILE = "checkpoint.pkl"
_FILES_KEY = "_checkpoint_files"   # dict key holding packed directory files

# Every tmp dir handed out by to_directory(path=None), so tests and
# long-lived drivers can reclaim them (they used to accumulate under
# /tmp/ray_tpu_ckpt for the life of the machine).
_TMP_REGISTRY: set = set()
_TMP_LOCK = threading.Lock()


class Checkpoint:
    def __init__(self, data: Optional[dict] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data/directory required")
        self._data = data
        self._dir = directory
        self._tmp_dirs: list = []

    # -------- constructors --------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    @classmethod
    def from_sharded_dir(cls, path: str,
                         validate: bool = True) -> "Checkpoint":
        """Wrap a `ray_tpu.checkpoint` sharded directory.  With
        `validate`, the directory must hold a manifest AND a COMMIT
        marker (pass False only for handles to still-in-flight saves)."""
        from ray_tpu.checkpoint import is_committed
        from ray_tpu.checkpoint.manifest import has_manifest
        if validate:
            if not has_manifest(path):
                raise ValueError(f"not a sharded checkpoint: {path}")
            if not is_committed(path):
                raise ValueError(
                    f"sharded checkpoint {path} has no COMMIT marker "
                    f"(torn or still being written)")
        return cls(directory=path)

    # -------- accessors --------

    @property
    def is_sharded(self) -> bool:
        """True for directory checkpoints in the sharded-manifest format."""
        if self._dir is None:
            return False
        from ray_tpu.checkpoint.manifest import has_manifest
        return has_manifest(self._dir)

    def to_pytree(self, *, mesh=None, shardings=None) -> Any:
        """Lossless interop with the sharded format: re-materialize the
        saved pytree (numpy by default; pass `mesh`/`shardings` to
        restore jax arrays under the CURRENT topology).  Dict-form
        checkpoints return their dict unchanged."""
        if self.is_sharded:
            from ray_tpu.checkpoint import restore_sharded
            return restore_sharded(self._dir, mesh=mesh, shardings=shardings)
        return self.to_dict()

    def to_dict(self) -> dict:
        """Dict form.  A directory checkpoint made from arbitrary files
        (e.g. orbax output) round-trips: every file is packed under the
        reserved _FILES_KEY (reference: air/checkpoint.py dict<->dir packs
        the full directory, _checkpoint.py _pack).  Sharded directories
        restore through their manifest instead — host numpy pytree out,
        not an opaque byte blob."""
        if self._data is not None:
            return dict(self._data)
        if self.is_sharded:
            tree = self.to_pytree()
            return tree if isinstance(tree, dict) else {"state": tree}
        pkl = os.path.join(self._dir, _DICT_FILE)
        data: dict = {}
        if os.path.isfile(pkl):
            with open(pkl, "rb") as f:
                data = pickle.load(f)
        files: dict = {}
        for root, _, names in os.walk(self._dir):
            for name in names:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, self._dir)
                if rel == _DICT_FILE:
                    continue
                with open(full, "rb") as f:
                    files[rel] = f.read()
        if files:
            data = dict(data)
            data[_FILES_KEY] = files
        return data

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(), "ray_tpu_ckpt",
                                uuid.uuid4().hex[:12])
            with _TMP_LOCK:
                _TMP_REGISTRY.add(path)
            self._tmp_dirs.append(path)
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            data = dict(self._data)
            files = dict(data.pop(_FILES_KEY, {}))
            if data or not files:
                buf = pickle.dumps(data)
                files[_DICT_FILE] = buf
            # Per-FILE atomic replace (an os.replace of a directory onto an
            # existing non-empty directory raises ENOTEMPTY).
            for rel, blob in files.items():
                dest = os.path.join(path, rel)
                parent = os.path.dirname(dest)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                tmpf = dest + ".tmp"
                with open(tmpf, "wb") as f:
                    f.write(blob)
                os.replace(tmpf, dest)
        return path

    def delete(self) -> None:
        """Reclaim this checkpoint's disk: its backing directory (if
        directory-form) and every tmp dir its to_directory(None) calls
        minted."""
        doomed = list(self._tmp_dirs)
        if self._dir is not None:
            doomed.append(self._dir)
        for path in doomed:
            shutil.rmtree(path, ignore_errors=True)
            with _TMP_LOCK:
                _TMP_REGISTRY.discard(path)
        self._tmp_dirs.clear()

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir={self._dir}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Sharded checkpoints ship as their (shared-filesystem) path —
        # packing shard files into a dict would both defeat the point
        # and race an in-flight save.  Plain checkpoints ship as dict
        # form so they survive crossing process boundaries even when
        # the directory is node-local.
        if self.is_sharded:
            return (Checkpoint.from_sharded_dir, (self._dir, False))
        return (Checkpoint.from_dict, (self.to_dict(),))


def cleanup_tmp() -> int:
    """Remove every tmp checkpoint dir this process created via
    to_directory(path=None); returns how many were swept."""
    with _TMP_LOCK:
        doomed = list(_TMP_REGISTRY)
        _TMP_REGISTRY.clear()
    for path in doomed:
        shutil.rmtree(path, ignore_errors=True)
    return len(doomed)
