"""Checkpoint: dict <-> directory interconvertible training state.

Reference parity: python/ray/air/checkpoint.py:63 (Checkpoint with
from_dict/to_dict/from_directory/to_directory/uri forms).  TPU idiom: the
dict form holds host numpy pytrees (device arrays are fetched before
checkpointing — orbax-style async device-to-host saving hooks in later).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Optional

_DICT_FILE = "checkpoint.pkl"


class Checkpoint:
    def __init__(self, data: Optional[dict] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data/directory required")
        self._data = data
        self._dir = directory

    # -------- constructors --------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    # -------- accessors --------

    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        with open(os.path.join(self._dir, _DICT_FILE), "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(), "ray_tpu_ckpt",
                                uuid.uuid4().hex[:12])
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            tmp = path + ".tmp"
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, _DICT_FILE), "wb") as f:
                pickle.dump(self._data, f)
            for name in os.listdir(tmp):
                os.replace(os.path.join(tmp, name), os.path.join(path, name))
            os.rmdir(tmp)
        return path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir={self._dir}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Ship as dict form so checkpoints survive crossing process
        # boundaries even when the directory is node-local.
        return (Checkpoint.from_dict, (self.to_dict(),))
