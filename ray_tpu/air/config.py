"""Shared run/scaling/failure/checkpoint configs.

Reference parity: python/ray/air/config.py — ScalingConfig:80,
FailureConfig:508, CheckpointConfig:567, RunConfig:695.  TPU twist:
`ScalingConfig` thinks in TPU hosts and slice topologies, not GPU counts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one holds.

    One worker == one process == one jax host (which may drive several TPU
    chips).  `use_tpu` reserves `tpus_per_worker` TPU resources per worker;
    `topology` (e.g. "v5p-128") lets a pod provisioner gang-schedule whole
    slices (a slice is atomic — reference GPUs scale per-device, TPU pods
    don't).
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float = 1.0
    resources_per_worker: Optional[dict] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None
    # Elastic floor: None (default) keeps the legacy fixed-size gang — a
    # restart waits for a full-size gang.  Set to k <= num_workers and a
    # restart may re-form on as few as k surviving workers (resize-down,
    # data re-sharded by the new world size) and grows back to
    # num_workers when capacity returns (resize-up at a step boundary).
    min_workers: Optional[int] = None

    def worker_resources(self) -> dict:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
        else:
            res = {"CPU": 1.0}
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = self.tpus_per_worker
        return res

    def as_placement_group_bundles(self) -> list[dict]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """Reference: air/config.py:508.  max_failures=-1 -> retry forever."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: air/config.py:567."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    """Reference: air/config.py:695."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    verbose: int = 1
