"""ray_tpu.air — shared config/checkpoint/session types.

Reference parity: python/ray/air/ (SURVEY.md §2.3 "Ray AIR glue").
"""

from ray_tpu.air.checkpoint import Checkpoint, cleanup_tmp  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train import session  # noqa: F401
