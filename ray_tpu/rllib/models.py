"""Policy/value networks for the RL stack, in flax.

Reference parity: rllib/models/ (ModelCatalog fcnet defaults) and the
minimal JAX stack the reference sketches in rllib/models/jax/fcnet.py —
here the JAX model IS the primary stack, not an afterthought.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class ActorCritic(nn.Module):
    """Separate-trunk MLP actor-critic with orthogonal init (PPO-standard)."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        ortho = nn.initializers.orthogonal
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(x))
        logits = nn.Dense(self.num_actions, kernel_init=ortho(0.01))(x)

        v = obs
        for h in self.hidden:
            v = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(v))
        value = nn.Dense(1, kernel_init=ortho(1.0))(v)
        return logits, jnp.squeeze(value, axis=-1)


class ConvActorCritic(nn.Module):
    """Nature-CNN actor-critic for image observations (the reference's
    ModelCatalog vision_net / Atari default: conv 32x8s4, 64x4s2, 64x3s1,
    dense 512 — one trunk, two heads).  Inputs are [B, H, W, C] in
    [0, 255]; scaling to [0, 1] happens inside so rollout buffers can
    stay uint8 (4x less memory/copy than float32)."""

    num_actions: int
    dense: int = 512

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = obs.astype(jnp.float32) / 255.0
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4))(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2))(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1))(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense)(x))
        logits = nn.Dense(self.num_actions,
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, jnp.squeeze(value, axis=-1)


def make_model(obs_dim, num_actions: int, hidden: Sequence[int] = (64, 64)):
    """Returns (init_params(rng), apply(params, obs) -> (logits, value)).

    `obs_dim` int = MLP on flat observations; a shape tuple (H, W, C) =
    Nature-CNN on images (reference: ModelCatalog dispatch by obs space)."""
    if isinstance(obs_dim, (tuple, list)) and len(obs_dim) > 1:
        model = ConvActorCritic(num_actions=num_actions)
        shape = tuple(obs_dim)

        def init_params(rng: jax.Array):
            return model.init(rng, jnp.zeros((1,) + shape, jnp.float32))

        return init_params, model.apply
    model = ActorCritic(num_actions=num_actions, hidden=tuple(hidden))

    def init_params(rng: jax.Array):
        dummy = jnp.zeros((1, int(obs_dim)), jnp.float32)
        return model.init(rng, dummy)

    return init_params, model.apply


class GaussianActorCritic(nn.Module):
    """Diagonal-Gaussian policy for continuous control: tanh MLP trunk ->
    action mean, a state-independent learned log_std, and a separate value
    trunk (reference: rllib fcnet w/ free_log_std for continuous spaces)."""

    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        ortho = nn.initializers.orthogonal
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(x))
        mean = nn.Dense(self.action_dim, kernel_init=ortho(0.01))(x)
        log_std = self.param("log_std", nn.initializers.zeros,
                             (self.action_dim,))

        v = obs
        for h in self.hidden:
            v = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(v))
        value = nn.Dense(1, kernel_init=ortho(1.0))(v)
        return mean, log_std, jnp.squeeze(value, axis=-1)


def make_continuous_model(obs_dim: int, action_dim: int,
                          hidden: Sequence[int] = (64, 64)):
    """(init_params(rng), apply(params, obs) -> (mean, log_std, value))."""
    model = GaussianActorCritic(action_dim=action_dim, hidden=tuple(hidden))

    def init_params(rng: jax.Array):
        dummy = jnp.zeros((1, obs_dim), jnp.float32)
        return model.init(rng, dummy)

    return init_params, model.apply


def gaussian_logp(mean, log_std, actions):
    """Diagonal-Gaussian log prob, summed over action dims."""
    var = jnp.exp(2 * log_std)
    return jnp.sum(
        -0.5 * ((actions - mean) ** 2 / var)
        - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)


class SquashedGaussianActor(nn.Module):
    """SAC actor: relu trunk -> state-dependent (mean, log_std); actions
    are tanh-squashed samples (reference: rllib/algorithms/sac policy
    model with SquashedGaussian action distribution)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


class DeterministicActor(nn.Module):
    """TD3/DDPG actor: relu trunk -> tanh action in [-1, 1] (env scaling
    applied by the caller)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.tanh(nn.Dense(self.action_dim)(x))


class QNetwork(nn.Module):
    """Continuous-action state-action value: Q(s, a) -> scalar."""

    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray):
        x = jnp.concatenate([obs, action], axis=-1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return jnp.squeeze(nn.Dense(1)(x), axis=-1)


def make_squashed_actor(obs_dim: int, action_dim: int,
                        hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs) -> (mean, log_std))."""
    model = SquashedGaussianActor(action_dim=action_dim,
                                  hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32))
    return init_params, model.apply


def make_deterministic_actor(obs_dim: int, action_dim: int,
                             hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs) -> action in [-1, 1])."""
    model = DeterministicActor(action_dim=action_dim, hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32))
    return init_params, model.apply


def make_q_network(obs_dim: int, action_dim: int,
                   hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs, action) -> q [B])."""
    model = QNetwork(hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32),
                          jnp.zeros((1, action_dim), jnp.float32))
    return init_params, model.apply
