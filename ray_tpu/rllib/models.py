"""Policy/value networks for the RL stack, in flax.

Reference parity: rllib/models/ (ModelCatalog fcnet defaults) and the
minimal JAX stack the reference sketches in rllib/models/jax/fcnet.py —
here the JAX model IS the primary stack, not an afterthought.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class ActorCritic(nn.Module):
    """Separate-trunk MLP actor-critic with orthogonal init (PPO-standard)."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        ortho = nn.initializers.orthogonal
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(x))
        logits = nn.Dense(self.num_actions, kernel_init=ortho(0.01))(x)

        v = obs
        for h in self.hidden:
            v = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(v))
        value = nn.Dense(1, kernel_init=ortho(1.0))(v)
        return logits, jnp.squeeze(value, axis=-1)


class ConvActorCritic(nn.Module):
    """Nature-CNN actor-critic for image observations (the reference's
    ModelCatalog vision_net / Atari default: conv 32x8s4, 64x4s2, 64x3s1,
    dense 512 — one trunk, two heads).  Inputs are [B, H, W, C] in
    [0, 255]; scaling to [0, 1] happens inside so rollout buffers can
    stay uint8 (4x less memory/copy than float32)."""

    num_actions: int
    dense: int = 512

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = obs.astype(jnp.float32) / 255.0
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4))(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2))(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1))(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense)(x))
        logits = nn.Dense(self.num_actions,
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, jnp.squeeze(value, axis=-1)


def make_model(obs_dim, num_actions: int, hidden: Sequence[int] = (64, 64)):
    """Returns (init_params(rng), apply(params, obs) -> (logits, value)).

    `obs_dim` int = MLP on flat observations; a shape tuple (H, W, C) =
    Nature-CNN on images (reference: ModelCatalog dispatch by obs space)."""
    if isinstance(obs_dim, (tuple, list)) and len(obs_dim) > 1:
        model = ConvActorCritic(num_actions=num_actions)
        shape = tuple(obs_dim)

        def init_params(rng: jax.Array):
            return model.init(rng, jnp.zeros((1,) + shape, jnp.float32))

        return init_params, model.apply
    model = ActorCritic(num_actions=num_actions, hidden=tuple(hidden))

    def init_params(rng: jax.Array):
        dummy = jnp.zeros((1, int(obs_dim)), jnp.float32)
        return model.init(rng, dummy)

    return init_params, model.apply


class GaussianActorCritic(nn.Module):
    """Diagonal-Gaussian policy for continuous control: tanh MLP trunk ->
    action mean, a state-independent learned log_std, and a separate value
    trunk (reference: rllib fcnet w/ free_log_std for continuous spaces)."""

    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        ortho = nn.initializers.orthogonal
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(x))
        mean = nn.Dense(self.action_dim, kernel_init=ortho(0.01))(x)
        log_std = self.param("log_std", nn.initializers.zeros,
                             (self.action_dim,))

        v = obs
        for h in self.hidden:
            v = nn.tanh(nn.Dense(h, kernel_init=ortho(np.sqrt(2)))(v))
        value = nn.Dense(1, kernel_init=ortho(1.0))(v)
        return mean, log_std, jnp.squeeze(value, axis=-1)


def make_continuous_model(obs_dim: int, action_dim: int,
                          hidden: Sequence[int] = (64, 64)):
    """(init_params(rng), apply(params, obs) -> (mean, log_std, value))."""
    model = GaussianActorCritic(action_dim=action_dim, hidden=tuple(hidden))

    def init_params(rng: jax.Array):
        dummy = jnp.zeros((1, obs_dim), jnp.float32)
        return model.init(rng, dummy)

    return init_params, model.apply


def gaussian_logp(mean, log_std, actions):
    """Diagonal-Gaussian log prob, summed over action dims."""
    var = jnp.exp(2 * log_std)
    return jnp.sum(
        -0.5 * ((actions - mean) ** 2 / var)
        - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)


class SquashedGaussianActor(nn.Module):
    """SAC actor: relu trunk -> state-dependent (mean, log_std); actions
    are tanh-squashed samples (reference: rllib/algorithms/sac policy
    model with SquashedGaussian action distribution)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


class DeterministicActor(nn.Module):
    """TD3/DDPG actor: relu trunk -> tanh action in [-1, 1] (env scaling
    applied by the caller)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.tanh(nn.Dense(self.action_dim)(x))


class QNetwork(nn.Module):
    """Continuous-action state-action value: Q(s, a) -> scalar."""

    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray):
        x = jnp.concatenate([obs, action], axis=-1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return jnp.squeeze(nn.Dense(1)(x), axis=-1)


def make_squashed_actor(obs_dim: int, action_dim: int,
                        hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs) -> (mean, log_std))."""
    model = SquashedGaussianActor(action_dim=action_dim,
                                  hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32))
    return init_params, model.apply


def make_deterministic_actor(obs_dim: int, action_dim: int,
                             hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs) -> action in [-1, 1])."""
    model = DeterministicActor(action_dim=action_dim, hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32))
    return init_params, model.apply


def make_q_network(obs_dim: int, action_dim: int,
                   hidden: Sequence[int] = (256, 256)):
    """(init(rng), apply(params, obs, action) -> q [B])."""
    model = QNetwork(hidden=tuple(hidden))

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, obs_dim), jnp.float32),
                          jnp.zeros((1, action_dim), jnp.float32))
    return init_params, model.apply


# ---------------------------------------------------------------------------
# Recurrent (LSTM) actor-critic.
#
# Reference parity: rllib/models/torch/recurrent_net.py (LSTMWrapper: an
# fcnet encoder feeding an LSTM whose hidden state threads through
# state_in/state_out) + rllib/policy/rnn_sequencing.py (training over
# fixed-length chunks with per-boundary state resets).  TPU-first
# differences: the cell is hand-rolled so training is one lax.scan with a
# masked carry reset at episode boundaries — static shapes, no ragged
# padding, everything fuses under jit.
# ---------------------------------------------------------------------------


def _dense_init(rng, n_in, n_out, scale=None):
    scale = np.sqrt(2.0 / n_in) if scale is None else scale
    return {"w": jax.random.normal(rng, (n_in, n_out)) * scale,
            "b": jnp.zeros((n_out,))}


def _dense(p, x):
    return x @ p["w"] + p["b"]


def _lstm_step(p, carry, x):
    """One LSTM step: carry = (h, c), gates in i/f/g/o order; forget-gate
    bias +1 (standard recurrent-training stabilizer)."""
    h, c = carry
    z = x @ p["wx"] + h @ p["wh"] + p["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return (h, c)


def make_recurrent_model(obs_dim: int, num_actions: int,
                         hidden: Sequence[int] = (64,),
                         lstm_size: int = 64):
    """Returns (init_params, apply_step, apply_seq, initial_state):

    - apply_step(params, obs[B,D], state[2,B,H]) ->
          (logits[B,A], value[B], state_out[2,B,H])   — rollout inference
    - apply_seq(params, obs[T,B,D], state0[2,B,H], resets[T,B]) ->
          (logits[T,B,A], values[T,B])                — chunked training;
      resets[t] True zeroes the carry BEFORE consuming step t (episode
      boundaries inside the chunk).
    - initial_state(batch) -> zeros [2, batch, lstm_size]
    """
    obs_dim = int(obs_dim)

    def init_params(rng: jax.Array):
        ks = jax.random.split(rng, len(hidden) + 4)
        enc = []
        n_in = obs_dim
        for i, h in enumerate(hidden):
            enc.append(_dense_init(ks[i], n_in, h))
            n_in = h
        k = len(hidden)
        lstm = {
            "wx": jax.random.normal(ks[k], (n_in, 4 * lstm_size))
            * np.sqrt(1.0 / n_in),
            "wh": jax.random.normal(ks[k + 1], (lstm_size, 4 * lstm_size))
            * np.sqrt(1.0 / lstm_size),
            "b": jnp.zeros((4 * lstm_size,)),
        }
        return {"enc": enc, "lstm": lstm,
                "pi": _dense_init(ks[k + 2], lstm_size, num_actions,
                                  scale=0.01),
                "vf": _dense_init(ks[k + 3], lstm_size, 1, scale=1.0)}

    def _encode(params, obs):
        x = obs
        for p in params["enc"]:
            x = jnp.tanh(_dense(p, x))
        return x

    def apply_step(params, obs, state):
        x = _encode(params, obs)
        h, c = _lstm_step(params["lstm"], (state[0], state[1]), x)
        return (_dense(params["pi"], h), _dense(params["vf"], h)[..., 0],
                jnp.stack([h, c]))

    def apply_seq(params, obs, state0, resets):
        x = _encode(params, obs)           # [T, B, E]

        def step(carry, inp):
            xt, rt = inp
            mask = (~rt)[:, None].astype(xt.dtype)
            carry = (carry[0] * mask, carry[1] * mask)
            carry = _lstm_step(params["lstm"], carry, xt)
            return carry, carry[0]

        _, hs = jax.lax.scan(step, (state0[0], state0[1]), (x, resets))
        return (_dense(params["pi"], hs),
                _dense(params["vf"], hs)[..., 0])

    def initial_state(batch: int) -> np.ndarray:
        return np.zeros((2, batch, lstm_size), np.float32)

    return init_params, apply_step, apply_seq, initial_state
