"""Off-policy estimators: evaluate a TARGET policy from logged data.

Reference parity: rllib/offline/estimators/ —
importance_sampling.py (per-decision IS), weighted_importance_sampling.py
(WIS: cumulative ratios normalized by their batch mean at each step),
direct_method.py (DM: a fitted Q-model queried under the target policy)
and doubly_robust.py (DR: the control-variate combination of both).

All estimators consume a logged SampleBatch with episode boundaries
(terminateds | truncateds), behavior log-probs (ACTION_LOGP) and rewards,
plus a `target_probs_fn(obs) -> [N, A]` giving the target policy's action
distribution.  DM/DR additionally need `q_fn(obs) -> [N, A]`.
Results follow the reference's shape: v_behavior / v_target / v_gain.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def split_episodes(batch: SampleBatch) -> List[Dict[str, np.ndarray]]:
    """Cut a row-major logged batch into per-episode dicts at
    terminated|truncated boundaries (trailing partial episode kept)."""
    done = (np.asarray(batch[SampleBatch.TERMINATEDS], bool)
            | np.asarray(batch[SampleBatch.TRUNCATEDS], bool))
    ends = np.flatnonzero(done) + 1
    bounds = [0, *ends.tolist()]
    if bounds[-1] != len(done):
        bounds.append(len(done))
    keys = list(batch.keys())
    return [{k: np.asarray(batch[k])[a:b] for k in keys}
            for a, b in zip(bounds[:-1], bounds[1:])]


class OffPolicyEstimator:
    """Base: per-episode estimates averaged over the batch."""

    def __init__(self, target_probs_fn: Callable, gamma: float = 0.99,
                 q_fn: Optional[Callable] = None):
        self.target_probs_fn = target_probs_fn
        self.gamma = gamma
        self.q_fn = q_fn

    # -- subclass hook -----------------------------------------------------
    def estimate_episode(self, ep: Dict[str, np.ndarray],
                         rho: np.ndarray) -> float:
        raise NotImplementedError

    # -- shared machinery --------------------------------------------------
    def _ratios(self, ep: Dict[str, np.ndarray]) -> np.ndarray:
        """Per-step importance ratios pi(a|s) / b(a|s)."""
        probs = np.asarray(self.target_probs_fn(ep[SampleBatch.OBS]))
        acts = ep[SampleBatch.ACTIONS].astype(int)
        pi = probs[np.arange(len(acts)), acts]
        b = np.exp(ep[SampleBatch.ACTION_LOGP])
        return pi / np.maximum(b, 1e-12)

    # Subclasses that never read the importance ratios (DM) skip the
    # per-episode target-policy forward pass entirely.
    needs_ratios = True

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        episodes = split_episodes(batch)
        # One forward pass per episode, shared by _prepare AND the
        # per-episode estimates (WIS used to pay it twice).
        rhos = ([self._ratios(ep) for ep in episodes]
                if self.needs_ratios else [None] * len(episodes))
        self._prepare(episodes, rhos)
        v_behavior, v_target = [], []
        for ep, rho in zip(episodes, rhos):
            g = self.gamma ** np.arange(len(ep[SampleBatch.REWARDS]))
            v_behavior.append(float((g * ep[SampleBatch.REWARDS]).sum()))
            v_target.append(self.estimate_episode(ep, rho))
        vb = float(np.mean(v_behavior))
        vt = float(np.mean(v_target))
        return {"v_behavior": vb, "v_target": vt, "v_gain": vt - vb,
                "episodes": len(episodes)}

    def _prepare(self, episodes, rhos) -> None:
        """Batch-level pre-pass (WIS normalization constants)."""


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision IS: V = E[ sum_t gamma^t (prod_{u<=t} rho_u) r_t ]
    (reference: importance_sampling.py)."""

    def estimate_episode(self, ep, rho):
        p = np.cumprod(rho)
        g = self.gamma ** np.arange(len(p))
        return float((g * p * ep[SampleBatch.REWARDS]).sum())


class WeightedImportanceSampling(OffPolicyEstimator):
    """WIS: cumulative ratios are normalized by their MEAN over the
    batch's episodes at each step index — biased but far lower variance
    (reference: weighted_importance_sampling.py)."""

    def _prepare(self, episodes, rhos) -> None:
        max_t = max((len(e[SampleBatch.REWARDS]) for e in episodes),
                    default=0)
        sums = np.zeros(max_t)
        counts = np.zeros(max_t)
        for rho in rhos:
            p = np.cumprod(rho)
            sums[:len(p)] += p
            counts[:len(p)] += 1
        self._w = sums / np.maximum(counts, 1)

    def estimate_episode(self, ep, rho):
        p = np.cumprod(rho)
        w = np.maximum(self._w[:len(p)], 1e-12)
        g = self.gamma ** np.arange(len(p))
        return float((g * (p / w) * ep[SampleBatch.REWARDS]).sum())


class DirectMethod(OffPolicyEstimator):
    """DM: the fitted Q-model's value of the target policy at episode
    starts, V = E_{a ~ pi}[Q(s_0, a)] (reference: direct_method.py; the
    reference fits the model with FQE — here any q_fn(obs) -> [N, A]
    plugs in, fit_fqe() below provides one)."""

    needs_ratios = False

    def estimate_episode(self, ep, rho):
        obs0 = ep[SampleBatch.OBS][:1]
        q = np.asarray(self.q_fn(obs0))[0]
        pi = np.asarray(self.target_probs_fn(obs0))[0]
        return float((pi * q).sum())


class DoublyRobust(OffPolicyEstimator):
    """DR: backward recursion
    V_t = vhat(s_t) + rho_t (r_t + gamma V_{t+1} - Q(s_t, a_t)),
    estimate = mean V_0 — unbiased if EITHER the ratios or the Q-model
    are correct (reference: doubly_robust.py:37)."""

    def estimate_episode(self, ep, rho):
        obs = ep[SampleBatch.OBS]
        acts = ep[SampleBatch.ACTIONS].astype(int)
        q = np.asarray(self.q_fn(obs))            # [T, A]
        pi = np.asarray(self.target_probs_fn(obs))
        vhat = (pi * q).sum(-1)                   # [T]
        q_taken = q[np.arange(len(acts)), acts]
        v_next = 0.0
        for t in range(len(acts) - 1, -1, -1):
            v_next = vhat[t] + rho[t] * (
                ep[SampleBatch.REWARDS][t] + self.gamma * v_next
                - q_taken[t])
        return float(v_next)


ESTIMATORS = {
    "is": ImportanceSampling,
    "wis": WeightedImportanceSampling,
    "dm": DirectMethod,
    "dr": DoublyRobust,
}


def fit_fqe(batch: SampleBatch, target_probs_fn: Callable,
            num_actions: int, gamma: float = 0.99,
            iterations: int = 200, lr: float = 1e-2,
            hidden=(64,), seed: int = 0) -> Callable:
    """Fitted Q Evaluation: learn Q^pi of the TARGET policy from logged
    transitions by bootstrapped regression (reference:
    offline/estimators/fqe_torch_model.py).  Returns q_fn(obs) -> [N, A]
    for DM/DR."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.models import make_model

    init_params, apply = make_model(
        np.asarray(batch[SampleBatch.OBS]).shape[-1], num_actions, hidden)
    params = init_params(jax.random.key(seed))
    tx = optax.adam(lr)
    opt = tx.init(params)

    obs = jnp.asarray(batch[SampleBatch.OBS], jnp.float32)
    acts = jnp.asarray(batch[SampleBatch.ACTIONS], jnp.int32)
    rew = jnp.asarray(batch[SampleBatch.REWARDS], jnp.float32)
    done = jnp.asarray(
        np.asarray(batch[SampleBatch.TERMINATEDS], bool)
        | np.asarray(batch[SampleBatch.TRUNCATEDS], bool))
    next_obs = jnp.concatenate([obs[1:], obs[-1:]], 0)
    pi_next = jnp.asarray(target_probs_fn(np.asarray(next_obs)),
                          jnp.float32)

    def qvals(p, o):
        logits, _ = apply(p, o)
        return logits    # reuse the fcnet head as Q-values

    @jax.jit
    def step(params, opt):
        def loss(p):
            q = qvals(p, obs)
            q_sa = jnp.take_along_axis(q, acts[:, None], 1)[:, 0]
            v_next = (pi_next * qvals(jax.lax.stop_gradient(p),
                                      next_obs)).sum(-1)
            target = rew + gamma * (1.0 - done) * v_next
            return ((q_sa - target) ** 2).mean()
        g = jax.grad(loss)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt

    for _ in range(iterations):
        params, opt = step(params, opt)

    def q_fn(o):
        return np.asarray(qvals(params, jnp.asarray(o, jnp.float32)))

    return q_fn
