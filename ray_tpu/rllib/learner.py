"""JaxLearner: jitted SGD on sample batches.

Reference parity: rllib/core/learner/learner.py:94 (compute_gradients:280,
apply_gradients:291, update:674) and torch_learner.py:45.  The TPU-first
difference: the ENTIRE update — epoch loop, minibatch permutation, grad,
optimizer step — is one jitted function (lax.scan over minibatches inside
lax.scan over epochs), so a training_step launches exactly one XLA program
instead of num_epochs*num_minibatches eager steps.  For multi-chip
learners the same function runs under shard_map with a psum on gradients
(data-parallel learner group, reference learner_group.py:51).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.models import (
    gaussian_logp,
    make_continuous_model,
    make_model,
)
from ray_tpu.rllib.sample_batch import SampleBatch


class JaxLearner:
    """Minibatch-SGD learner over an ActorCritic model.

    loss_fn(apply, params, minibatch, cfg) -> (loss, metrics) is supplied
    by the algorithm (PPO/IMPALA define theirs below/in impala.py).
    """

    def __init__(self, obs_dim: int, num_actions: int, *,
                 loss_fn: Callable, config: Dict[str, Any],
                 hidden=(64, 64), seed: int = 0,
                 mesh: Optional[Any] = None, action_dim: int = 0,
                 model: str = "fc", lstm_size: int = 64):
        self.config = config
        if model == "lstm":
            from ray_tpu.rllib.models import make_recurrent_model
            init_params, _step, self.apply, self.initial_state = \
                make_recurrent_model(obs_dim, num_actions, hidden,
                                     lstm_size)
        elif num_actions == 0 and action_dim > 0:
            init_params, self.apply = make_continuous_model(
                obs_dim, action_dim, hidden)
        else:
            init_params, self.apply = make_model(obs_dim, num_actions,
                                                 hidden)
        self.params = init_params(jax.random.key(seed))
        lr = config.get("lr", 3e-4)
        sched = lr
        if config.get("lr_schedule") == "linear":
            sched = optax.linear_schedule(
                lr, 0.0, config.get("lr_decay_steps", 1000))
        self.tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(sched, eps=1e-5),
        )
        self.opt_state = self.tx.init(self.params)
        self._loss_fn = loss_fn
        self._rng = jax.random.key(seed + 17)
        # Data-parallel learner group over the mesh's data axis
        # (reference: learner_group.py:51 — a fleet of DDP-wrapped
        # learners; here one SPMD program with a pmean on gradients).
        self.mesh = None
        if mesh is not None and any(s > 1 for s in mesh.shape.values()):
            bad = [a for a, s in mesh.shape.items()
                   if s > 1 and a != "data"]
            if bad:
                raise ValueError(
                    f"JaxLearner is data-parallel only; mesh axes {bad} "
                    f"have size > 1 (shard the model with models/, not "
                    f"the RL learner)")
            self.mesh = mesh
        make = self._make_update_dp if self.mesh else self._make_update
        self._update = jax.jit(make(), donate_argnums=(0, 1))

    def _make_update(self):
        num_epochs = self.config.get("num_sgd_iter", 1)
        mb_size = self.config.get("sgd_minibatch_size", 128)
        loss_fn, apply, tx, cfg = self._loss_fn, self.apply, self.tx, self.config

        def minibatch_step(carry, mb):
            params, opt_state = carry
            (_, metrics), grads = jax.value_and_grad(
                partial(loss_fn, apply), has_aux=True)(params, mb, cfg)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        def update(params, opt_state, batch, rng):
            n = jax.tree_util.tree_leaves(batch)[0].shape[0]
            num_mb = max(n // mb_size, 1)
            take = num_mb * min(mb_size, n)

            def epoch_step(carry, rng_e):
                params, opt_state = carry
                perm = jax.random.permutation(rng_e, n)
                mbs = jax.tree_util.tree_map(
                    lambda x: x[perm][:take].reshape(
                        (num_mb, take // num_mb) + x.shape[1:]), batch)
                (params, opt_state), metrics = jax.lax.scan(
                    minibatch_step, (params, opt_state), mbs)
                return (params, opt_state), metrics

            rngs = jax.random.split(rng, num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch_step, (params, opt_state), rngs)
            mean_metrics = jax.tree_util.tree_map(
                lambda m: jnp.mean(m), metrics)
            return params, opt_state, mean_metrics

        return update

    def _make_update_dp(self):
        """SPMD data-parallel update: every shard holds the full batch
        (replicated in_specs), computes identical global permutations and
        per-minibatch advantage normalization, then takes ITS slice of
        each minibatch; gradients pmean over the data axis reconstruct
        the exact global-minibatch gradient, so a dp-k learner walks the
        same parameter trajectory as a single chip (up to fp summation
        order — regression-gated in tests/test_rllib_dp.py)."""
        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.mesh import shard_map_compat

        mesh = self.mesh
        k = mesh.shape["data"]
        num_epochs = self.config.get("num_sgd_iter", 1)
        mb_size = self.config.get("sgd_minibatch_size", 128)
        loss_fn, apply, tx = self._loss_fn, self.apply, self.tx
        # Normalization already applied globally per minibatch below.
        cfg = dict(self.config)
        cfg["advantages_prenormalized"] = True

        def minibatch_step(carry, mb):
            params, opt_state = carry
            (_, metrics), grads = jax.value_and_grad(
                partial(loss_fn, apply), has_aux=True)(params, mb, cfg)
            grads = jax.lax.pmean(grads, "data")
            metrics = jax.lax.pmean(metrics, "data")
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        def shard_update(params, opt_state, batch, rng):
            idx = jax.lax.axis_index("data")
            n = jax.tree_util.tree_leaves(batch)[0].shape[0]
            num_mb = max(n // mb_size, 1)
            mb_rows = (min(mb_size, n) // k) * k   # divisible by k
            take = num_mb * mb_rows
            local_rows = mb_rows // k

            def epoch_step(carry, rng_e):
                params, opt_state = carry
                perm = jax.random.permutation(rng_e, n)  # same every shard
                mbs = jax.tree_util.tree_map(
                    lambda x: x[perm][:take].reshape(
                        (num_mb, mb_rows) + x.shape[1:]), batch)
                if SampleBatch.ADVANTAGES in mbs:
                    adv = mbs[SampleBatch.ADVANTAGES]
                    # Normalize over every non-minibatch axis (recurrent
                    # batches carry a time axis after the row axis).
                    ax = tuple(range(1, adv.ndim))
                    mbs[SampleBatch.ADVANTAGES] = (
                        (adv - adv.mean(ax, keepdims=True))
                        / (adv.std(ax, keepdims=True) + 1e-8))
                local = jax.tree_util.tree_map(
                    lambda x: jax.lax.dynamic_slice_in_dim(
                        x, idx * local_rows, local_rows, axis=1), mbs)
                (params, opt_state), metrics = jax.lax.scan(
                    minibatch_step, (params, opt_state), local)
                return (params, opt_state), metrics

            rngs = jax.random.split(rng, num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch_step, (params, opt_state), rngs)
            mean_metrics = jax.tree_util.tree_map(
                lambda m: jnp.mean(m), metrics)
            return params, opt_state, mean_metrics

        return shard_map_compat(shard_update, mesh,
                                (P(), P(), P(), P()), (P(), P(), P()))

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, jbatch, sub)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)

    def get_state(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])


def policy_terms(apply, params, mb, cfg=None):
    """Shared per-minibatch terms: (values, taken-action logp, normalized
    advantages, entropy) — used by the PPO and A2C losses."""
    logits, values = apply(params, mb[SampleBatch.OBS])
    logp_all = jax.nn.log_softmax(logits)
    actions = mb[SampleBatch.ACTIONS].astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
    adv = mb[SampleBatch.ADVANTAGES]
    if not (cfg or {}).get("advantages_prenormalized"):
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    return values, logp, adv, entropy


def _ppo_surrogate(mb, cfg, values, logp, entropy):
    """Shared clipped-surrogate + clamped-vf assembly used by the discrete
    and Gaussian PPO losses (reference semantics: ppo_torch_policy.py —
    SQUARED vf error clamped at vf_clip_param, zero-gradding outliers)."""
    clip = cfg.get("clip_param", 0.2)
    vf_clip = cfg.get("vf_clip_param", 100.0)
    vf_coeff = cfg.get("vf_loss_coeff", 0.5)
    ent_coeff = cfg.get("entropy_coeff", 0.0)

    adv = mb[SampleBatch.ADVANTAGES]
    if not cfg.get("advantages_prenormalized"):
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    ratio = jnp.exp(logp - mb[SampleBatch.ACTION_LOGP])
    surr = jnp.minimum(ratio * adv,
                       jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    policy_loss = -surr.mean()
    vf_loss = jnp.minimum(
        (values - mb[SampleBatch.VALUE_TARGETS]) ** 2, vf_clip).mean()
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {"total_loss": total, "policy_loss": policy_loss,
                   "vf_loss": vf_loss, "entropy": entropy,
                   "kl": (mb[SampleBatch.ACTION_LOGP] - logp).mean()}


def ppo_loss(apply, params, mb, cfg) -> Tuple[jnp.ndarray, Dict]:
    """Clipped-surrogate PPO loss (categorical actions)."""
    values, logp, _adv, entropy = policy_terms(apply, params, mb)
    return _ppo_surrogate(mb, cfg, values, logp, entropy)


def ppo_loss_recurrent(apply_seq, params, mb, cfg) -> Tuple[jnp.ndarray,
                                                            Dict]:
    """Clipped-surrogate PPO over LSTM sequence chunks.  Minibatch rows
    are SEQUENCES: OBS [b, T, D], actions/logp/advantages/targets
    [b, T], resets [b, T], state_in [b, 2, H] (reference:
    rnn_sequencing.py chunked training — here a masked-reset lax.scan
    replay instead of padded variable-length sequences)."""
    obs = jnp.moveaxis(mb[SampleBatch.OBS], 0, 1)        # [T, b, D]
    resets = mb["resets"].T                              # [T, b]
    state0 = jnp.moveaxis(mb["state_in"], 0, 1)          # [2, b, H]
    logits, values = apply_seq(params, obs, state0, resets)
    logits = jnp.moveaxis(logits, 0, 1)                  # [b, T, A]
    values = values.T                                    # [b, T]
    logp_all = jax.nn.log_softmax(logits)
    actions = mb[SampleBatch.ACTIONS].astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, actions[..., None],
                               axis=-1)[..., 0]
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    return _ppo_surrogate(mb, cfg, values, logp, entropy)


def ppo_loss_continuous(apply, params, mb, cfg) -> Tuple[jnp.ndarray, Dict]:
    """Clipped-surrogate PPO for diagonal-Gaussian policies (reference:
    ppo loss over DiagGaussian action dists)."""
    mean, log_std, values = apply(params, mb[SampleBatch.OBS])
    logp = gaussian_logp(mean, log_std, mb[SampleBatch.ACTIONS])
    # Diagonal-Gaussian entropy: 0.5*log(2*pi*e) + log_std per dim.
    entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e))
    return _ppo_surrogate(mb, cfg, values, logp, entropy)
