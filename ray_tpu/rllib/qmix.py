"""QMIX / VDN: value-decomposition multi-agent Q-learning.

Reference parity: rllib/algorithms/qmix/ (qmix.py, qmix_policy.py
mixers) — cooperative agents learn per-agent utilities Q_i(o_i, a_i)
combined into a team value Q_tot by a MONOTONIC mixing network whose
weights are produced by hypernetworks of the global state (Rashid et
al. 2018); VDN (Sunehag et al. 2017) is the linear special case
Q_tot = sum_i Q_i.  Monotonicity (non-negative mixing weights) makes
the per-agent argmax consistent with the joint argmax, so execution
stays decentralized while training is centralized.

Everything is one jitted TD step over replay minibatches: agent nets
(shared parameters, vmapped over agents) + hypernet mixer + target
copies.  The global state defaults to the concatenation of agent
observations when the env does not expose one.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.multi_agent import (
    MultiAgentVectorEnv,
    make_multi_agent_env,
    register_multi_agent_env,
)


class TwoStepGameEnv(MultiAgentVectorEnv):
    """The QMIX paper's two-step cooperative matrix game (Rashid et al.
    2018, section 5.1): agent a0's FIRST action picks the second-step
    game — 2A pays 7 for every joint action; 2B pays [[0,1],[1,8]].
    The optimum (pick 2B, then both play 1 -> 8) is invisible to purely
    additive mixing: VDN settles on the safe 7, QMIX's state-conditioned
    monotonic mixer recovers 8 — the canonical separation test."""

    agent_ids = ("a0", "a1")
    observation_dims = {"a0": 3, "a1": 3}   # one-hot state s0/s2A/s2B
    num_actions_by_agent = {"a0": 2, "a1": 2}
    PAYOFF_2B = np.array([[0.0, 1.0], [1.0, 8.0]])

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._state = np.zeros(num_envs, np.int64)   # 0=s0, 1=s2A, 2=s2B

    def _obs(self) -> Dict[str, np.ndarray]:
        onehot = np.eye(3, dtype=np.float32)[self._state]
        return {a: onehot.copy() for a in self.agent_ids}

    def reset_all(self, seed: Optional[int] = None):
        self._state[:] = 0
        for a in self.agent_ids:
            self._ep_return[a][:] = 0.0
        self._ep_len[:] = 0
        return self._obs()

    def step_batch(self, actions: Dict[str, np.ndarray]):
        a0 = np.asarray(actions["a0"])
        a1 = np.asarray(actions["a1"])
        in_s0 = self._state == 0
        team = np.zeros(self.num_envs, np.float32)
        # Step 2 payoffs:
        in_2a = self._state == 1
        in_2b = self._state == 2
        team[in_2a] = 7.0
        team[in_2b] = self.PAYOFF_2B[a0[in_2b], a1[in_2b]]
        terminated = ~in_s0
        # Step-1 transition: a0's action selects the matrix game.
        nxt = np.where(a0 == 0, 1, 2)
        self._state = np.where(in_s0, nxt, 0)   # done envs auto-reset
        rew = {a: team / 2.0 for a in self.agent_ids}  # team split
        return self._obs(), rew, terminated, np.zeros(self.num_envs, bool)


register_multi_agent_env("two-step-game", TwoStepGameEnv)


class QMixConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=QMix)
        self.env = "two-step-game"
        # One shared net across (homogeneous) agents — declare the map so
        # the Algorithm base probes the env as multi-agent.
        self.policies = ["shared"]
        self.policy_mapping_fn = lambda aid: "shared"
        self.mixer = "qmix"              # "qmix" | "vdn"
        self.mixing_embed_dim = 16
        self.num_envs_per_worker = 16
        self.lr = 5e-3
        self.gamma = 0.99
        self.buffer_size = 4096
        self.train_batch_size = 128
        self.epsilon_timesteps = 2000    # linear 1.0 -> 0.05
        self.final_epsilon = 0.05
        self.target_update_interval = 100
        self.rollout_steps_per_iter = 64
        self.train_steps_per_iter = 16
        self.model_hidden = (64,)


class QMix(Algorithm):
    def setup(self) -> None:
        import jax
        cfg = self.config
        self.env = make_multi_agent_env(cfg.env, cfg.num_envs_per_worker,
                                        seed=cfg.seed)
        self.agents: List[str] = list(self.env.agent_ids)
        self.n_agents = len(self.agents)
        # Homogeneous-agent assumption (shared net, vmapped): dims match.
        dims = set(self.env.observation_dims.values())
        acts = set(self.env.num_actions_by_agent.values())
        if len(dims) != 1 or len(acts) != 1:
            raise ValueError("QMIX here shares one agent net: all agents "
                             "need identical obs/action spaces")
        self.agent_obs_dim = dims.pop()
        self.n_actions = acts.pop()
        self.state_dim = self.agent_obs_dim * self.n_agents
        self._rng = np.random.default_rng(cfg.seed)
        self.params = self._init_params(jax.random.key(cfg.seed))
        self.target_params = self.params
        import optax
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._buf: List[Any] = []
        self._buf_pos = 0
        self._env_obs = self.env.reset_all(seed=cfg.seed)
        self._steps_sampled = 0
        self._train_steps = 0
        self.workers = None
        self._build_fns()

    # -- parameters --------------------------------------------------------
    def _init_params(self, key):
        import jax
        cfg = self.config
        h = cfg.model_hidden[0]
        e = cfg.mixing_embed_dim
        ks = jax.random.split(key, 8)

        def dense(k, n_in, n_out):
            import jax.numpy as jnp
            w = jax.random.normal(k, (n_in, n_out)) / jnp.sqrt(n_in)
            return {"w": w.astype(jnp.float32),
                    "b": jnp.zeros(n_out, jnp.float32)}

        params = {
            "agent1": dense(ks[0], self.agent_obs_dim, h),
            "agent2": dense(ks[1], h, self.n_actions),
        }
        if cfg.mixer == "qmix":
            params.update({
                "hyper_w1": dense(ks[2], self.state_dim,
                                  self.n_agents * e),
                "hyper_b1": dense(ks[3], self.state_dim, e),
                "hyper_w2": dense(ks[4], self.state_dim, e),
                "hyper_b2_1": dense(ks[5], self.state_dim, e),
                "hyper_b2_2": dense(ks[6], e, 1),
            })
        return params

    def _build_fns(self):
        import jax
        import jax.numpy as jnp
        cfg = self.config
        n_agents, e = self.n_agents, cfg.mixing_embed_dim
        gamma = cfg.gamma
        mixer = cfg.mixer

        def lin(p, x):
            return x @ p["w"] + p["b"]

        def agent_q(params, obs):            # [.., obs_dim] -> [.., A]
            return lin(params["agent2"],
                       jnp.tanh(lin(params["agent1"], obs)))

        def mix(params, qs, state):
            """qs [B, n_agents] -> Q_tot [B]; monotone in every q_i."""
            if mixer == "vdn":
                return qs.sum(-1)
            w1 = jnp.abs(lin(params["hyper_w1"], state)).reshape(
                -1, n_agents, e)
            b1 = lin(params["hyper_b1"], state)
            hidden = jax.nn.elu(
                jnp.einsum("bn,bne->be", qs, w1) + b1)
            w2 = jnp.abs(lin(params["hyper_w2"], state))
            b2 = lin(params["hyper_b2_2"], jax.nn.relu(
                lin(params["hyper_b2_1"], state)))[:, 0]
            return (hidden * w2).sum(-1) + b2

        def td_loss(params, target_params, obs, actions, team_rew,
                    next_obs, dones):
            # obs [B, n_agents, obs_dim]; actions [B, n_agents]
            B = obs.shape[0]
            state = obs.reshape(B, -1)
            next_state = next_obs.reshape(B, -1)
            q_all = agent_q(params, obs)               # [B, n, A]
            q_taken = jnp.take_along_axis(
                q_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
            q_tot = mix(params, q_taken, state)
            # Decentralized-consistent target: per-agent argmax under the
            # TARGET net, mixed by the target mixer.
            tq_all = agent_q(target_params, next_obs)
            tq_max = tq_all.max(-1)
            t_tot = mix(target_params, tq_max, next_state)
            y = team_rew + gamma * (1.0 - dones) * t_tot
            return ((q_tot - jax.lax.stop_gradient(y)) ** 2).mean()

        def train_step(params, target_params, opt_state, obs, actions,
                       team_rew, next_obs, dones):
            import optax
            l, grads = jax.value_and_grad(td_loss)(
                params, target_params, obs, actions, team_rew, next_obs,
                dones)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l

        self._agent_q = jax.jit(agent_q)
        self._train_step = jax.jit(train_step)

    # -- rollout / replay --------------------------------------------------
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._steps_sampled / cfg.epsilon_timesteps)
        return 1.0 + frac * (cfg.final_epsilon - 1.0)

    def _act(self, obs: Dict[str, np.ndarray], explore=True
             ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp
        stacked = np.stack([obs[a] for a in self.agents], 1)  # [n_env,n,O]
        q = np.asarray(self._agent_q(self.params, jnp.asarray(stacked)))
        greedy = q.argmax(-1)                                 # [n_env, n]
        if explore:
            eps = self._epsilon()
            rnd = self._rng.integers(0, self.n_actions, greedy.shape)
            mask = self._rng.random(greedy.shape) < eps
            greedy = np.where(mask, rnd, greedy)
        return {a: greedy[:, i] for i, a in enumerate(self.agents)}

    def _store(self, trans):
        if len(self._buf) < self.config.buffer_size:
            self._buf.append(trans)
        else:
            self._buf[self._buf_pos] = trans
            self._buf_pos = (self._buf_pos + 1) % self.config.buffer_size

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        cfg = self.config
        for _ in range(cfg.rollout_steps_per_iter):
            obs = self._env_obs
            actions = self._act(obs)
            nobs, rew, term, trunc = self.env.step(actions)
            team = sum(np.asarray(rew[a], np.float32)
                       for a in self.agents)
            done = (term | trunc).astype(np.float32)
            o = np.stack([obs[a] for a in self.agents], 1)
            no = np.stack([nobs[a] for a in self.agents], 1)
            acts = np.stack([np.asarray(actions[a]) for a in self.agents],
                            1)
            for i in range(self.env.num_envs):
                self._store((o[i], acts[i], team[i], no[i], done[i]))
            self._env_obs = nobs
            self._steps_sampled += self.env.num_envs
        losses = []
        if len(self._buf) >= cfg.train_batch_size:
            for _ in range(cfg.train_steps_per_iter):
                idx = self._rng.integers(0, len(self._buf),
                                         cfg.train_batch_size)
                o, a, r, no, d = (np.stack(x) for x in zip(
                    *[self._buf[i] for i in idx]))
                self.params, self.opt_state, l = self._train_step(
                    self.params, self.target_params, self.opt_state,
                    jnp.asarray(o, jnp.float32), jnp.asarray(a),
                    jnp.asarray(r), jnp.asarray(no, jnp.float32),
                    jnp.asarray(d))
                losses.append(float(l))
                self._train_steps += 1
                if self._train_steps % cfg.target_update_interval == 0:
                    self.target_params = self.params
        rets, lens = self.env.drain_episode_metrics()
        # Team return = sum of the agents' per-episode returns.
        team_rets = [sum(vals) for vals in zip(*rets.values())]
        self._episode_returns.extend(team_rets)
        self._episode_lengths.extend(lens)
        self.total_env_steps += cfg.rollout_steps_per_iter * \
            self.env.num_envs
        return {"episodes_this_iter": len(team_rets),
                "epsilon": self._epsilon(),
                "td_loss": float(np.mean(losses)) if losses else np.nan}

    def evaluate_greedy(self, episodes: int = 64) -> float:
        """Mean TEAM return under the greedy decentralized policies."""
        env = make_multi_agent_env(self.config.env, episodes,
                                   seed=self.config.seed + 1)
        obs = env.reset_all()
        total = np.zeros(episodes, np.float64)
        # Episodes are masked, not restarted (es.py idiom): each lane
        # accumulates team reward until its FIRST done, then goes
        # inactive — auto-reset lanes must not leak a second episode's
        # reward into the mean.
        active = np.ones(episodes, bool)
        for _ in range(64):
            actions = self._act(obs, explore=False)
            obs, rew, term, trunc = env.step(actions)
            team_rew = sum(np.asarray(rew[a]) for a in self.agents)
            total += team_rew * active
            active &= ~(term | trunc)
            if not active.any():
                break
        return float(total.mean())

    def save_to_dict(self) -> Dict[str, Any]:
        import jax
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "steps_sampled": self._steps_sampled}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self._steps_sampled = state["steps_sampled"]


class VDNConfig(QMixConfig):
    """VDN = additive mixing (reference: qmix.py's mixer=None/'vdn')."""

    def __init__(self):
        super().__init__()
        self.mixer = "vdn"
