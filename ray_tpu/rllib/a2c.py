"""A2C: synchronous advantage actor-critic.

Reference parity: rllib/algorithms/a2c/a2c.py — PPO's synchronous
sample/update plumbing with the plain policy-gradient loss (no ratio
clipping, single pass over the batch).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib.learner import JaxLearner
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sample_batch import SampleBatch


def a2c_loss(apply, params, mb, cfg) -> Tuple[jnp.ndarray, Dict]:
    from ray_tpu.rllib.learner import policy_terms

    vf_coeff = cfg.get("vf_loss_coeff", 0.5)
    ent_coeff = cfg.get("entropy_coeff", 0.0)

    values, logp, adv, entropy = policy_terms(apply, params, mb, cfg)
    policy_loss = -(logp * adv).mean()
    vf_loss = ((values - mb[SampleBatch.VALUE_TARGETS]) ** 2).mean()
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {"total_loss": total, "policy_loss": policy_loss,
                   "vf_loss": vf_loss, "entropy": entropy}


class A2CConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = A2C
        # On-policy single pass, as in the reference A2C.
        self.num_sgd_iter = 1
        self.sgd_minibatch_size = 0   # 0 = whole batch
        self.train_batch_size = 2048
        self.lr = 1e-3
        self.entropy_coeff = 0.01


class A2C(PPO):
    def _make_learner(self) -> JaxLearner:
        cfg = self.config
        mb = cfg.sgd_minibatch_size or cfg.train_batch_size
        return JaxLearner(
            self.obs_dim, self.num_actions, loss_fn=a2c_loss,
            config={"lr": cfg.lr, "grad_clip": cfg.grad_clip,
                    "num_sgd_iter": cfg.num_sgd_iter,
                    "sgd_minibatch_size": mb,
                    "vf_loss_coeff": getattr(cfg, "vf_loss_coeff", 0.5),
                    "entropy_coeff": getattr(cfg, "entropy_coeff", 0.0)},
            hidden=cfg.model_hidden, seed=cfg.seed,
            mesh=cfg.learner_mesh)
