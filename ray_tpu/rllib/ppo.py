"""PPO: Proximal Policy Optimization.

Reference parity: rllib/algorithms/ppo/ppo.py:343 (training_step:384 —
synchronous parallel sample -> standardize -> minibatch SGD -> weight
broadcast) with the loss of ppo_torch_policy.py.  TPU-first difference:
the whole SGD phase is one jitted XLA program (see learner.py) and weight
broadcast is one object-store put.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner, ppo_loss, ppo_loss_continuous
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.clip_param = 0.2
        self.vf_clip_param = 100.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.005
        self.lr = 5e-4
        self.train_batch_size = 4096
        self.sgd_minibatch_size = 256
        self.num_sgd_iter = 10


class PPO(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        self.workers = WorkerSet(
            num_workers=cfg.num_rollout_workers,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, lam=cfg.lambda_,
                hidden=cfg.model_hidden, seed=cfg.seed, postprocess=True))
        self.learner = self._make_learner()
        self.workers.sync_weights(self.learner.get_weights())

    def _make_learner(self) -> JaxLearner:
        """Overridable learner factory (A2C swaps the loss/config here
        without re-running worker construction or double weight syncs)."""
        cfg = self.config
        return JaxLearner(
            self.obs_dim, self.num_actions, action_dim=self.action_dim,
            loss_fn=(ppo_loss_continuous if self.continuous else ppo_loss),
            config={
                "lr": cfg.lr, "grad_clip": cfg.grad_clip,
                "num_sgd_iter": cfg.num_sgd_iter,
                "sgd_minibatch_size": cfg.sgd_minibatch_size,
                "clip_param": getattr(cfg, "clip_param", 0.2),
                "vf_clip_param": getattr(cfg, "vf_clip_param", 100.0),
                "vf_loss_coeff": getattr(cfg, "vf_loss_coeff", 0.5),
                "entropy_coeff": getattr(cfg, "entropy_coeff", 0.0),
            },
            hidden=cfg.model_hidden, seed=cfg.seed,
            mesh=cfg.learner_mesh)

    def training_step(self) -> Dict[str, Any]:
        """Reference: ppo.py:384."""
        # 1. Synchronous parallel sampling until train_batch_size rows.
        batches, all_metrics = [], []
        rows = 0
        while rows < self.config.train_batch_size:
            bs, ms = self.workers.sample_sync()
            batches.extend(bs)
            all_metrics.extend(ms)
            rows += sum(b.count for b in bs)
        train_batch = SampleBatch.concat_samples(batches)
        episodes = self._record_metrics(all_metrics)

        # 2. Minibatch SGD — one jitted XLA program.
        learner_metrics = self.learner.update(train_batch)

        # 3. Weight broadcast via object store.
        self.workers.sync_weights(self.learner.get_weights())

        return {"sampled_rows": train_batch.count,
                "episodes_this_iter": episodes,
                **{f"learner/{k}": v for k, v in learner_metrics.items()}}

    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
