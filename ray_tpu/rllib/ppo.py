"""PPO: Proximal Policy Optimization.

Reference parity: rllib/algorithms/ppo/ppo.py:343 (training_step:384 —
synchronous parallel sample -> standardize -> minibatch SGD -> weight
broadcast) with the loss of ppo_torch_policy.py.  TPU-first difference:
the whole SGD phase is one jitted XLA program (see learner.py) and weight
broadcast is one object-store put.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import (
    JaxLearner,
    ppo_loss,
    ppo_loss_continuous,
    ppo_loss_recurrent,
)
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.clip_param = 0.2
        self.vf_clip_param = 100.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.005
        self.lr = 5e-4
        self.train_batch_size = 4096
        self.sgd_minibatch_size = 256
        self.num_sgd_iter = 10


class PPO(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        if self.multi_agent:
            from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker
            self.workers = WorkerSet(
                num_workers=cfg.num_rollout_workers,
                num_cpus_per_worker=cfg.num_cpus_per_worker,
                worker_cls=MultiAgentRolloutWorker,
                worker_kwargs=dict(
                    env=cfg.env, num_envs=cfg.num_envs_per_worker,
                    rollout_fragment_length=cfg.rollout_fragment_length,
                    gamma=cfg.gamma, lam=cfg.lambda_,
                    hidden=cfg.model_hidden, seed=cfg.seed,
                    policies=dict.fromkeys(cfg.policies),
                    policy_mapping_fn=cfg.policy_mapping_fn))
            # One learner per policy (reference: Learner per module in the
            # MultiRLModule, learner_group.py).
            self.learners = {pid: self._make_learner(spec)
                             for pid, spec in self.policy_specs.items()}
            self.workers.sync_weights(
                {pid: ln.get_weights() for pid, ln in self.learners.items()})
            return
        self.workers = WorkerSet(
            num_workers=cfg.num_rollout_workers,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, lam=cfg.lambda_,
                hidden=cfg.model_hidden, seed=cfg.seed, postprocess=True,
                **({"policy_kind": "recurrent",
                    "lstm_size": cfg.lstm_size} if cfg.use_lstm else {})))
        self.learner = self._make_learner()
        self.workers.sync_weights(self.learner.get_weights())

    def _make_learner(self, spec=None) -> JaxLearner:
        """Overridable learner factory (A2C swaps the loss/config here
        without re-running worker construction or double weight syncs).
        `spec` = (obs_dim, num_actions) for a multi-agent policy."""
        cfg = self.config
        obs_dim, num_actions = spec if spec else (self.obs_dim,
                                                  self.num_actions)
        use_lstm = getattr(cfg, "use_lstm", False)
        if use_lstm:
            loss = ppo_loss_recurrent
        elif self.continuous:
            loss = ppo_loss_continuous
        else:
            loss = ppo_loss
        return JaxLearner(
            obs_dim, num_actions, action_dim=self.action_dim,
            model=("lstm" if use_lstm else "fc"),
            lstm_size=getattr(cfg, "lstm_size", 64),
            loss_fn=loss,
            config={
                "lr": cfg.lr, "grad_clip": cfg.grad_clip,
                "num_sgd_iter": cfg.num_sgd_iter,
                "sgd_minibatch_size": cfg.sgd_minibatch_size,
                "clip_param": getattr(cfg, "clip_param", 0.2),
                "vf_clip_param": getattr(cfg, "vf_clip_param", 100.0),
                "vf_loss_coeff": getattr(cfg, "vf_loss_coeff", 0.5),
                "entropy_coeff": getattr(cfg, "entropy_coeff", 0.0),
            },
            hidden=cfg.model_hidden, seed=cfg.seed,
            mesh=cfg.learner_mesh)

    def training_step(self) -> Dict[str, Any]:
        """Reference: ppo.py:384."""
        # 1. Synchronous parallel sampling until train_batch_size rows.
        batches, all_metrics = [], []
        rows = 0
        while rows < self.config.train_batch_size:
            bs, ms = self.workers.sample_sync()
            batches.extend(bs)
            all_metrics.extend(ms)
            rows += sum(b.count for b in bs)
        episodes = self._record_metrics(all_metrics)

        if self.multi_agent:
            from ray_tpu.rllib.multi_agent import MultiAgentBatch
            train_batch = MultiAgentBatch.concat_samples(batches)
            # 2. Per-policy minibatch SGD (each one jitted XLA program).
            learner_metrics = {}
            for pid, sub in train_batch.policy_batches.items():
                for k, v in self.learners[pid].update(sub).items():
                    learner_metrics[f"{pid}/{k}"] = v
            # 3. Broadcast the whole policy map in one put.
            self.workers.sync_weights(
                {pid: ln.get_weights() for pid, ln in self.learners.items()})
            # Per-policy improvement signal for multi-agent gates.
            per_policy_returns: Dict[str, list] = {}
            mapping = self.config.policy_mapping_fn or (lambda a: a)
            for m in all_metrics:
                for aid, rs in m.get("per_agent_returns", {}).items():
                    per_policy_returns.setdefault(mapping(aid),
                                                  []).extend(rs)
            import numpy as _np
            extra = {f"policy_reward_mean/{pid}": float(_np.mean(rs))
                     for pid, rs in per_policy_returns.items() if rs}
            return {"sampled_rows": train_batch.count,
                    "episodes_this_iter": episodes, **extra,
                    **{f"learner/{k}": v
                       for k, v in learner_metrics.items()}}

        train_batch = SampleBatch.concat_samples(batches)

        # 2. Minibatch SGD — one jitted XLA program.
        learner_metrics = self.learner.update(train_batch)

        # 3. Weight broadcast via object store.
        self.workers.sync_weights(self.learner.get_weights())

        return {"sampled_rows": train_batch.count,
                "episodes_this_iter": episodes,
                **{f"learner/{k}": v for k, v in learner_metrics.items()}}

    def save_to_dict(self) -> Dict[str, Any]:
        if self.multi_agent:
            return {"learner_state": {pid: ln.get_state()
                                      for pid, ln in self.learners.items()},
                    "config": self.config.to_dict()}
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        if self.multi_agent:
            for pid, st in state["learner_state"].items():
                self.learners[pid].set_state(st)
            self.workers.sync_weights(
                {pid: ln.get_weights() for pid, ln in self.learners.items()})
            return
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
