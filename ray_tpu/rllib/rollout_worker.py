"""RolloutWorker: experience collection on (CPU) actors.

Reference parity: rllib/evaluation/rollout_worker.py:166 (sample:879,
get_weights:1718/set_weights:1756) + sampler.py's env loop (_env_runner:529).
Differences are deliberate and TPU-first: the env is natively vectorized
(one numpy step for all sub-envs), the policy forward pass is one jitted
call per timestep over the whole env batch, and postprocessing (GAE) is
vectorized over the fragment.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


def _force_cpu_platform_if_worker() -> None:
    """Pin jax to the CPU platform inside remote worker processes.

    Must run before the process's first jax computation (config changes
    after backend init are ignored).  JAX_PLATFORMS env alone is not
    enough: the TPU bootstrap re-selects its platform at import time.
    """
    try:
        from ray_tpu import api
        if api._worker is None or api._worker.mode != "worker":
            return
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


class RolloutWorker:
    """Steps a vectorized env with the current policy and emits SampleBatches.

    Runs as a ray_tpu actor (one per CPU slot) but is also directly usable
    in-process (the local-worker mode the reference uses for num_workers=0).
    """

    def __init__(self, env: Any, *, num_envs: int = 8,
                 rollout_fragment_length: int = 64,
                 gamma: float = 0.99, lam: float = 0.95,
                 hidden=(64, 64), seed: int = 0,
                 postprocess: bool = True,
                 epsilon_schedule=None,
                 policy_kind: str = "actor_critic",
                 lstm_size: int = 64,
                 exploration_noise: float = 0.1,
                 random_warmup_steps: int = 0,
                 exploration=None,
                 obs_connector=None,
                 action_connector=None):
        # In a remote worker process, force the whole jax platform to CPU
        # before the first jax use: rollout actors must not even initialize
        # the TPU runtime (one chip, many actor processes).  In the driver
        # the platform is left alone (the learner owns the chip) and the
        # policy pins itself to the CPU backend instead.
        _force_cpu_platform_if_worker()
        self.env = make_vector_env(env, num_envs, seed=seed)
        self.num_envs = num_envs
        self.fragment_length = rollout_fragment_length
        self.gamma, self.lam = gamma, lam
        self.postprocess = postprocess
        action_dim = getattr(self.env, "action_dim", 0)
        num_actions = getattr(self.env, "num_actions", 0)
        self.continuous = num_actions == 0 and action_dim > 0
        if num_actions == 0 and action_dim == 0:
            raise ValueError(
                f"env {env!r} must declare num_actions (discrete) or "
                f"action_dim (continuous)")
        if epsilon_schedule is not None and self.continuous:
            raise ValueError(
                "epsilon-greedy exploration requires a discrete env")
        action_low = getattr(self.env, "action_low", -1.0)
        action_high = getattr(self.env, "action_high", 1.0)
        # An obs connector can reshape what the policy sees; size the
        # model from a transformed sample, not the raw env spec.
        policy_obs_dim = self.env.observation_dim
        if obs_connector is not None:
            probe = obs_connector(self.env.reset_all(seed))
            policy_obs_dim = (probe.shape[1] if probe.ndim == 2
                              else tuple(probe.shape[1:]))
        self._rnn_state = None
        if policy_kind == "recurrent":
            from ray_tpu.rllib.policy import RecurrentJaxPolicy
            self.policy = RecurrentJaxPolicy(
                policy_obs_dim, self.env.num_actions, hidden,
                lstm_size=lstm_size, seed=seed)
            self._rnn_state = self.policy.initial_state(num_envs)
        elif policy_kind == "actor_critic":
            self.policy = JaxPolicy(
                policy_obs_dim, self.env.num_actions, hidden,
                seed=seed, action_dim=action_dim,
                action_low=action_low, action_high=action_high)
        elif policy_kind == "squashed_gaussian":      # SAC behavior policy
            from ray_tpu.rllib.policy import SquashedGaussianRolloutPolicy
            self.policy = SquashedGaussianRolloutPolicy(
                self.env.observation_dim, action_dim, hidden, seed=seed,
                action_low=action_low, action_high=action_high)
        elif policy_kind == "deterministic_noise":    # TD3 behavior policy
            from ray_tpu.rllib.policy import DeterministicNoiseRolloutPolicy
            self.policy = DeterministicNoiseRolloutPolicy(
                self.env.observation_dim, action_dim, hidden, seed=seed,
                action_low=action_low, action_high=action_high,
                noise_scale=exploration_noise)
        else:
            raise ValueError(f"unknown policy_kind {policy_kind!r}")
        # Uniform-random action warmup before the policy takes over
        # (reference: SAC/TD3 configs' num_steps_sampled_before_learning /
        # random_timesteps exploration option).
        self._random_warmup = int(random_warmup_steps)
        self._action_low, self._action_high = action_low, action_high
        self.obs = self.env.reset_all(seed)
        self._total_steps = 0
        # Epsilon-greedy exploration for value-based algorithms
        # (reference: rllib/utils/exploration/epsilon_greedy.py):
        # (initial, final, decay_steps) linear schedule on env steps.
        self._epsilon_schedule = epsilon_schedule
        self._np_rng = np.random.default_rng(seed + 99)
        # Pluggable exploration + connector pipelines (reference:
        # rllib/utils/exploration/ and rllib/connectors/): the obs
        # connector transforms observations INTO the policy (recorded
        # batches hold the transformed obs, as the learner must see what
        # the policy saw); the action connector transforms actions OUT to
        # the env only — training stores the raw policy actions.
        self._exploration = exploration
        self._obs_connector = obs_connector
        self._action_connector = action_connector
        if self._obs_connector is not None:
            self.obs = self._obs_connector(self.obs)

    # -- weights -----------------------------------------------------------
    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    # -- sampling ----------------------------------------------------------
    def sample(self) -> Tuple[SampleBatch, Dict]:
        """Collect one fragment: [T, B] steps, T=fragment_length, B=num_envs.

        Returns (batch, metrics).  With postprocess=True the batch is
        flattened to [T*B] rows with GAE advantages/value targets (PPO
        path); otherwise it stays time-major [T, B, ...] with behavior
        logits (IMPALA/V-trace path).
        """
        if self._rnn_state is not None:
            return self._sample_recurrent()
        T, B = self.fragment_length, self.num_envs
        # Image envs declare a shape tuple + uint8 observations; buffers
        # follow the (possibly connector-transformed) obs the policy
        # actually sees, at its dtype, so pixels move at 1 byte each.
        obs_buf = np.empty((T, B) + self.obs.shape[1:], self.obs.dtype)
        if self.continuous:
            adim = self.env.action_dim
            act_buf = np.empty((T, B, adim), np.float32)
            logits_buf = np.empty((T, B, adim), np.float32)  # means
        else:
            act_buf = np.empty((T, B), np.int32)
            logits_buf = np.empty((T, B, self.env.num_actions), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), np.bool_)
        trunc_buf = np.empty((T, B), np.bool_)
        logp_buf = np.empty((T, B), np.float32)
        vf_buf = np.empty((T, B), np.float32)

        obs = self.obs
        for t in range(T):
            # Value-based (epsilon) mode acts GREEDILY on Q plus epsilon
            # noise; policy-gradient mode samples the distribution.
            actions, logp, vf, logits = self.policy.compute_actions(
                obs, explore=self._epsilon_schedule is None)
            if self._epsilon_schedule is not None:
                e0, e1, decay = self._epsilon_schedule
                frac = min(1.0, self._total_steps / max(decay, 1))
                eps = e0 + (e1 - e0) * frac
                explore_mask = self._np_rng.random(B) < eps
                random_actions = self._np_rng.integers(
                    0, self.env.num_actions, size=B)
                actions = np.where(explore_mask, random_actions, actions)
            if self.continuous and self._total_steps + t * B < \
                    self._random_warmup:
                actions = self._np_rng.uniform(
                    self._action_low, self._action_high,
                    size=(B, self.env.action_dim)).astype(np.float32)
            if self._exploration is not None:
                actions = self._exploration.apply(
                    actions, self._total_steps + t * B, self._np_rng)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            vf_buf[t] = vf
            logits_buf[t] = logits
            env_actions = (self._action_connector(actions)
                           if self._action_connector is not None
                           else actions)
            obs, rew, term, trunc = self.env.step(env_actions)
            if self._obs_connector is not None:
                obs = self._obs_connector(obs)
            rew_buf[t] = rew
            term_buf[t] = term
            trunc_buf[t] = trunc
        self.obs = obs
        self._total_steps += T * B

        rets, lens = self.env.drain_episode_metrics()
        metrics = {"episode_returns": rets, "episode_lengths": lens,
                   "env_steps": T * B, "total_env_steps": self._total_steps}

        if not self.postprocess:
            batch = SampleBatch({
                SampleBatch.OBS: obs_buf, SampleBatch.ACTIONS: act_buf,
                SampleBatch.REWARDS: rew_buf,
                SampleBatch.TERMINATEDS: term_buf,
                SampleBatch.TRUNCATEDS: trunc_buf,
                SampleBatch.ACTION_LOGP: logp_buf,
                SampleBatch.ACTION_LOGITS: logits_buf,
                "bootstrap_obs": self.obs,
            })
            return batch, metrics

        # GAE. Episodes end at terminated|truncated (auto-reset envs); a
        # truncated boundary still cuts the advantage chain, which slightly
        # underestimates returns there but keeps the fragment math simple.
        done = term_buf | trunc_buf
        _, _, bootstrap_vf, _ = self.policy.compute_actions(self.obs)
        adv, targets = compute_gae(rew_buf, vf_buf, done, bootstrap_vf,
                                   self.gamma, self.lam)
        flat = lambda x: x.reshape((T * B,) + x.shape[2:])
        batch = SampleBatch({
            SampleBatch.OBS: flat(obs_buf),
            SampleBatch.ACTIONS: flat(act_buf),
            SampleBatch.ACTION_LOGP: flat(logp_buf),
            SampleBatch.VF_PREDS: flat(vf_buf),
            SampleBatch.ADVANTAGES: flat(adv),
            SampleBatch.VALUE_TARGETS: flat(targets),
        })
        return batch, metrics

    def _sample_recurrent(self) -> Tuple[SampleBatch, Dict]:
        """Fragment collection with LSTM state threading (reference:
        sampler state_batches + rnn_sequencing).  The chunk IS the
        max_seq_len unit: training replays the whole [T] fragment from
        the recorded initial state, zeroing the carry at episode
        boundaries via the `resets` mask — the static-shape equivalent
        of the reference's padded sequence batches.

        Batch layout: postprocess=True -> sequence-major [B, T, ...]
        rows (the learner minibatches over SEQUENCES); otherwise
        time-major [T, B, ...] for the V-trace path.  Extra columns:
        state_in ([B, 2, H] / [2, B, H]), resets, dones."""
        T, B = self.fragment_length, self.num_envs
        obs_buf = np.empty((T, B) + self.obs.shape[1:], self.obs.dtype)
        act_buf = np.empty((T, B), np.int32)
        logits_buf = np.empty((T, B, self.env.num_actions), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), np.bool_)
        trunc_buf = np.empty((T, B), np.bool_)
        logp_buf = np.empty((T, B), np.float32)
        vf_buf = np.empty((T, B), np.float32)
        resets_buf = np.zeros((T, B), np.bool_)

        state_in = self._rnn_state.copy()    # [2, B, H] at fragment start
        obs = self.obs
        state = self._rnn_state
        for t in range(T):
            actions, logp, vf, logits, state = \
                self.policy.compute_actions(obs, state)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            vf_buf[t] = vf
            logits_buf[t] = logits
            env_actions = (self._action_connector(actions)
                           if self._action_connector is not None
                           else actions)
            obs, rew, term, trunc = self.env.step(env_actions)
            if self._obs_connector is not None:
                obs = self._obs_connector(obs)
            rew_buf[t] = rew
            term_buf[t] = term
            trunc_buf[t] = trunc
            done = term | trunc
            if done.any():
                # Auto-reset envs: zero the carry for finished episodes;
                # the NEXT consumed step starts fresh (mirrored by the
                # resets mask during training).  Copy: the policy returns
                # a read-only view of a device buffer.
                state = state.copy()
                state[:, done, :] = 0.0
                if t + 1 < T:
                    resets_buf[t + 1, done] = True
        self.obs = obs
        self._rnn_state = state
        self._total_steps += T * B

        rets, lens = self.env.drain_episode_metrics()
        metrics = {"episode_returns": rets, "episode_lengths": lens,
                   "env_steps": T * B, "total_env_steps": self._total_steps}

        if not self.postprocess:
            batch = SampleBatch({
                SampleBatch.OBS: obs_buf, SampleBatch.ACTIONS: act_buf,
                SampleBatch.REWARDS: rew_buf,
                SampleBatch.TERMINATEDS: term_buf,
                SampleBatch.TRUNCATEDS: trunc_buf,
                SampleBatch.ACTION_LOGP: logp_buf,
                SampleBatch.ACTION_LOGITS: logits_buf,
                "state_in": state_in,         # [2, B, H]
                "resets": resets_buf,         # [T, B]
                "bootstrap_obs": self.obs,
                "bootstrap_state": self._rnn_state.copy(),
            })
            return batch, metrics

        done = term_buf | trunc_buf
        _, _, bootstrap_vf, _, _ = self.policy.compute_actions(
            self.obs, self._rnn_state)
        adv, targets = compute_gae(rew_buf, vf_buf, done, bootstrap_vf,
                                   self.gamma, self.lam)
        seq = lambda x: np.moveaxis(x, 0, 1)   # [T,B,...] -> [B,T,...]
        batch = SampleBatch({
            SampleBatch.OBS: seq(obs_buf),
            SampleBatch.ACTIONS: seq(act_buf),
            SampleBatch.ACTION_LOGP: seq(logp_buf),
            SampleBatch.VF_PREDS: seq(vf_buf),
            SampleBatch.ADVANTAGES: seq(adv),
            SampleBatch.VALUE_TARGETS: seq(targets),
            "resets": seq(resets_buf),                    # [B, T]
            "state_in": np.moveaxis(state_in, 0, 1),      # [B, 2, H]
        })
        return batch, metrics

    def evaluate(self, num_episodes: int = 10,
                 max_steps: int = 1000) -> Dict:
        """Greedy-policy evaluation rollouts."""
        self.env.drain_episode_metrics()
        returns: list = []
        obs = self.obs
        steps = 0
        if self._rnn_state is not None:
            state = self.policy.initial_state(self.num_envs)
            while len(returns) < num_episodes and steps < max_steps:
                actions, _, _, _, state = self.policy.compute_actions(
                    obs, state, explore=False)
                if self._action_connector is not None:
                    actions = self._action_connector(actions)
                obs, _, term, trunc = self.env.step(actions)
                if self._obs_connector is not None:
                    obs = self._obs_connector(obs)
                done = term | trunc
                if done.any():
                    state = state.copy()
                    state[:, done, :] = 0.0
                steps += 1
                rets, _ = self.env.drain_episode_metrics()
                returns.extend(rets)
            self.obs = obs
            self._rnn_state = self.policy.initial_state(self.num_envs)
            return {"episode_returns": returns}
        while len(returns) < num_episodes and steps < max_steps:
            actions, _, _, _ = self.policy.compute_actions(obs, explore=False)
            if self._action_connector is not None:
                actions = self._action_connector(actions)
            obs, _, _, _ = self.env.step(actions)
            if self._obs_connector is not None:
                obs = self._obs_connector(obs)
            steps += 1
            rets, _ = self.env.drain_episode_metrics()
            returns.extend(rets)
        self.obs = obs
        return {"episode_returns": returns}

    def ping(self) -> bool:
        return True
