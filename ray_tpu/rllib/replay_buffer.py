"""Replay buffers for off-policy RL.

Reference parity: rllib/utils/replay_buffers/ (ReplayBuffer uniform
sampling; prioritized variant uses segment trees — here proportional
prioritization is computed directly over the priority array, which at
typical buffer sizes (<=1e6) is a single vectorized numpy pass).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring buffer over SampleBatch rows."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._storage: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._storage:
            for k, v in batch.items():
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_replay_buffer.py): P(i) ~ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta normalized by max."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._priorities = np.zeros(capacity, np.float64)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_priority

    def sample(self, batch_size: int, beta: float = 0.4) -> SampleBatch:
        prios = self._priorities[:self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-beta)
        weights /= weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._storage.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(priorities) + 1e-6
        self._priorities[idx] = priorities
        self._max_priority = max(self._max_priority,
                                 float(priorities.max()))
