"""Augmented Random Search (Mania et al. 2018).

Reference parity: rllib/algorithms/ars/ — the V1-t/V2-t variants: only
the top-b directions (by best-of-pair return) contribute, the step is
normalized by the std of the surviving returns, and V2 normalizes
observations with a running mean/std filter aggregated from the worker
fleet.  Shares the batched-vmapped EvalWorker with ES (es.py) — same
seed-coded antithetic perturbations, one jitted rollout per worker call.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.es import ES, _init_flat


class ARSConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=ARS)
        self.num_rollout_workers = 2
        self.episodes_per_batch = 16     # directions sampled per iter
        self.top_directions = 8          # b: directions kept for the step
        self.noise_stdev = 0.05
        self.lr = 0.02
        self.episode_horizon = 500
        self.observation_filter = "MeanStdFilter"   # "NoFilter" = V1
        self.model_hidden = (32,)


class ARS(ES):
    def setup(self) -> None:
        super().setup()
        cfg = self.config
        # V2 observation filter state (aggregated across the fleet).
        self._obs_n = 1e-4
        self._obs_sum = np.zeros(self.obs_dim, np.float64)
        self._obs_sq = np.full(self.obs_dim, 1e-4, np.float64)

    def _obs_stats(self):
        if self.config.observation_filter != "MeanStdFilter":
            return None
        mean = self._obs_sum / self._obs_n
        var = np.maximum(self._obs_sq / self._obs_n - mean ** 2, 1e-8)
        return mean.astype(np.float32), np.sqrt(var).astype(np.float32)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_dir = cfg.episodes_per_batch
        b = min(cfg.top_directions, n_dir)
        seeds = self._rng.integers(0, 2 ** 31 - 1, size=n_dir)
        stats = self._obs_stats()
        results, shards = self._fan_out(seeds, stats)
        r_plus = np.concatenate([r["r_plus"] for r in results])
        r_minus = np.concatenate([r["r_minus"] for r in results])
        used = np.concatenate(shards)
        # Fold the fleet's observation moments into the running filter
        # (reference: ars.py filter synchronization each iteration).
        for r in results:
            self._obs_n += r["obs_n"]
            self._obs_sum += r["obs_sum"]
            self._obs_sq += r["obs_sq"]
        # Top-b directions by best-of-pair (V1-t/V2-t selection).
        order = np.argsort(-np.maximum(r_plus, r_minus))[:b]
        kept = np.concatenate([r_plus[order], r_minus[order]])
        sigma_r = kept.std() + 1e-8
        eps = np.stack([
            np.random.default_rng(int(used[i]))
            .standard_normal(self.theta.size).astype(np.float32)
            for i in order])
        step = ((r_plus[order] - r_minus[order])[:, None] * eps).sum(0)
        self.theta += cfg.lr / (b * sigma_r) * step

        all_returns = np.concatenate([r_plus, r_minus])
        lengths = np.concatenate([r["lengths"] for r in results])
        self._episode_returns.extend(all_returns.tolist())
        self._episode_lengths.extend(lengths.tolist())
        self.total_env_steps += int(lengths.sum())
        return {"episodes_this_iter": int(all_returns.size),
                "sigma_r": float(sigma_r),
                "theta_norm": float(np.linalg.norm(self.theta))}

    def save_to_dict(self) -> Dict[str, Any]:
        d = super().save_to_dict()
        d.update({"obs_n": self._obs_n, "obs_sum": self._obs_sum,
                  "obs_sq": self._obs_sq})
        return d

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        super().restore_from_dict(state)
        self._obs_n = state["obs_n"]
        self._obs_sum = state["obs_sum"]
        self._obs_sq = state["obs_sq"]
