"""V-trace off-policy correction (IMPALA), as a jittable lax.scan.

Reference parity: rllib/algorithms/impala/vtrace_torch.py — the
importance-weighted value targets and policy-gradient advantages of
Espeholt et al. 2018, computed here as one reverse lax.scan over the
time-major fragment so the whole thing fuses into the learner's XLA
program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class VTraceReturns(NamedTuple):
    vs: jnp.ndarray            # [T, B] value targets
    pg_advantages: jnp.ndarray  # [T, B]


def vtrace(behavior_logp: jnp.ndarray, target_logp: jnp.ndarray,
           rewards: jnp.ndarray, discounts: jnp.ndarray,
           values: jnp.ndarray, bootstrap_value: jnp.ndarray,
           clip_rho_threshold: float = 1.0,
           clip_c_threshold: float = 1.0) -> VTraceReturns:
    """All args time-major [T, B]; bootstrap_value [B].

    discounts must already include termination masking
    (gamma * (1 - done)).
    """
    log_rhos = target_logp - behavior_logp
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    cs = jnp.minimum(clip_c_threshold, rhos)

    values_tp1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def backward(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values

    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return VTraceReturns(vs=jax.lax.stop_gradient(vs),
                         pg_advantages=jax.lax.stop_gradient(pg_advantages))
