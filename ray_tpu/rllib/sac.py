"""SAC: soft actor-critic for continuous control.

Reference parity: rllib/algorithms/sac/ (sac.py config surface: twin Q
networks, tanh-squashed Gaussian policy, automatic entropy-temperature
tuning against a target entropy, polyak target updates; training_step is
the generic store-rollouts -> replay-sample -> update loop shared with
DQN).  TPU-first shape: the whole SAC update — critic TD step on
min(Q1',Q2') soft targets, actor reparameterized step, alpha step, and
the polyak averaging — is ONE jitted XLA program over a train-state
pytree; nothing crosses the host boundary between the three optimizers.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.dqn import _to_transitions
from ray_tpu.rllib.models import make_q_network, make_squashed_actor
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.tau = 0.005                   # polyak coefficient
        self.initial_alpha = 1.0
        self.target_entropy = None         # default: -action_dim
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1_500
        self.random_warmup_steps = 1_000   # uniform actions at the start
        self.train_batch_size = 256
        self.updates_per_step = 32
        self.model_hidden = (256, 256)


class _SACState(NamedTuple):
    actor: Any
    q1: Any
    q2: Any
    q1_t: Any
    q2_t: Any
    log_alpha: jnp.ndarray
    actor_opt: Any
    critic_opt: Any
    alpha_opt: Any
    rng: jax.Array


def _squashed_sample(apply, params, obs, rng, scale, center):
    """Reparameterized tanh-Gaussian sample in env scale + its log-prob
    (with the tanh + affine change-of-variables correction)."""
    mean, log_std = apply(params, obs)
    u = mean + jnp.exp(log_std) * jax.random.normal(rng, mean.shape)
    # logp of u under N(mean, std)
    logp_u = jnp.sum(
        -0.5 * ((u - mean) ** 2) * jnp.exp(-2 * log_std)
        - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
    t = jnp.tanh(u)
    # d(tanh)/du = 1 - tanh^2; numerically-stable log1p form.
    log_det = jnp.sum(jnp.log(scale * (1 - t ** 2) + 1e-6), axis=-1)
    return t * scale + center, logp_u - log_det


class _SACLearner:
    def __init__(self, obs_dim: int, action_dim: int, cfg: SACConfig,
                 action_low, action_high, seed: int):
        hidden = cfg.model_hidden
        init_actor, actor_apply = make_squashed_actor(obs_dim, action_dim,
                                                      hidden)
        init_q, q_apply = make_q_network(obs_dim, action_dim, hidden)
        k = jax.random.split(jax.random.key(seed), 4)
        actor = init_actor(k[0])
        q1, q2 = init_q(k[1]), init_q(k[2])
        scale = jnp.asarray((np.asarray(action_high)
                             - np.asarray(action_low)) / 2.0, jnp.float32)
        center = jnp.asarray((np.asarray(action_high)
                              + np.asarray(action_low)) / 2.0, jnp.float32)
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(action_dim))
        actor_tx = optax.adam(cfg.actor_lr)
        critic_tx = optax.adam(cfg.critic_lr)
        alpha_tx = optax.adam(cfg.alpha_lr)
        log_alpha = jnp.asarray(np.log(cfg.initial_alpha), jnp.float32)
        self.state = _SACState(
            actor=actor, q1=q1, q2=q2, q1_t=q1, q2_t=q2,
            log_alpha=log_alpha,
            actor_opt=actor_tx.init(actor),
            critic_opt=critic_tx.init((q1, q2)),
            alpha_opt=alpha_tx.init(log_alpha),
            rng=jax.random.key(seed + 7))
        gamma, tau = cfg.gamma, cfg.tau
        self.num_updates = 0

        def step(state: _SACState, batch):
            rng, k_next, k_pi = jax.random.split(state.rng, 3)
            alpha = jnp.exp(state.log_alpha)

            # -- critic: soft TD target from the target twins --
            next_a, next_logp = _squashed_sample(
                actor_apply, state.actor, batch["next_obs"], k_next,
                scale, center)
            q_next = jnp.minimum(
                q_apply(state.q1_t, batch["next_obs"], next_a),
                q_apply(state.q2_t, batch["next_obs"], next_a))
            target = batch["rewards"] + gamma * (
                1.0 - batch["dones"].astype(jnp.float32)) * (
                q_next - alpha * next_logp)
            target = jax.lax.stop_gradient(target)

            def critic_loss(qs):
                p1, p2 = qs
                e1 = q_apply(p1, batch["obs"], batch["actions"]) - target
                e2 = q_apply(p2, batch["obs"], batch["actions"]) - target
                return (e1 ** 2 + e2 ** 2).mean()

            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                (state.q1, state.q2))
            c_updates, critic_opt = critic_tx.update(
                c_grads, state.critic_opt, (state.q1, state.q2))
            q1, q2 = optax.apply_updates((state.q1, state.q2), c_updates)

            # -- actor: maximize E[min Q - alpha logp] (reparameterized) --
            def actor_loss(ap):
                a_pi, logp_pi = _squashed_sample(
                    actor_apply, ap, batch["obs"], k_pi, scale, center)
                q_pi = jnp.minimum(q_apply(q1, batch["obs"], a_pi),
                                   q_apply(q2, batch["obs"], a_pi))
                return (alpha * logp_pi - q_pi).mean(), logp_pi

            (a_loss, logp_pi), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state.actor)
            a_updates, actor_opt = actor_tx.update(
                a_grads, state.actor_opt, state.actor)
            actor = optax.apply_updates(state.actor, a_updates)

            # -- temperature: drive policy entropy toward the target --
            def alpha_loss(la):
                return -(la * jax.lax.stop_gradient(
                    logp_pi + target_entropy)).mean()

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                state.log_alpha)
            al_update, alpha_opt = alpha_tx.update(
                al_grad, state.alpha_opt, state.log_alpha)
            log_alpha = optax.apply_updates(state.log_alpha, al_update)

            # -- polyak target update --
            polyak = lambda t, s: jax.tree.map(
                lambda a, b: (1 - tau) * a + tau * b, t, s)
            new_state = _SACState(
                actor=actor, q1=q1, q2=q2,
                q1_t=polyak(state.q1_t, q1), q2_t=polyak(state.q2_t, q2),
                log_alpha=log_alpha, actor_opt=actor_opt,
                critic_opt=critic_opt, alpha_opt=alpha_opt, rng=rng)
            metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha_loss": al_loss, "alpha": jnp.exp(log_alpha),
                       "entropy": -logp_pi.mean()}
            return new_state, metrics

        self._step = jax.jit(step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.state, metrics = self._step(self.state, jb)
        self.num_updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.state.actor)

    def get_state(self):
        s = jax.device_get(self.state._replace(rng=None))
        return {"sac_state": s._asdict(), "num_updates": self.num_updates}

    def set_state(self, state):
        d = dict(state["sac_state"])
        d["rng"] = self.state.rng
        self.state = _SACState(**jax.device_put(d))
        self.num_updates = state.get("num_updates", 0)


class SAC(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        if not self.continuous:
            raise ValueError("SAC requires a continuous-action env")
        self.workers = WorkerSet(
            num_workers=cfg.num_rollout_workers,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, hidden=cfg.model_hidden, seed=cfg.seed,
                postprocess=False, policy_kind="squashed_gaussian",
                random_warmup_steps=cfg.random_warmup_steps))
        probe = self.workers.local_worker.env
        self.learner = _SACLearner(
            self.obs_dim, self.action_dim, cfg,
            probe.action_low, probe.action_high, cfg.seed)
        from ray_tpu.rllib.replay_buffer import ReplayBuffer
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self.workers.sync_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        """Reference: sac.py training_step (via DQN's generic loop) —
        sample -> store -> N gradient updates -> weight broadcast."""
        cfg = self.config
        batches, metrics_list = self.workers.sample_sync()
        episodes = self._record_metrics(metrics_list)
        for b in batches:
            self.buffer.add(_to_transitions(b))

        learner_metrics: Dict[str, float] = {}
        updates = 0
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_step):
                learner_metrics = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
                updates += 1
            self.workers.sync_weights(self.learner.get_weights())

        return {"episodes_this_iter": episodes,
                "buffer_size": len(self.buffer),
                "learner_updates_total": self.learner.num_updates,
                "updates_this_iter": updates,
                **{f"learner/{k}": v for k, v in learner_metrics.items()}}

    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
