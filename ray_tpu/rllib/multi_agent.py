"""Multi-agent RL: env contract, per-policy batches, rollout worker.

Reference parity: rllib/env/multi_agent_env.py (MultiAgentEnv — dict
obs/rewards keyed by agent id), rllib/policy/sample_batch.py
(MultiAgentBatch: {policy_id: SampleBatch} + env_steps) and the policy
mapping machinery of rllib/algorithms/algorithm_config.py (.multi_agent
policies + policy_mapping_fn).  TPU-first difference: the env is natively
VECTORIZED per agent — one [B, ...] numpy step covers all sub-envs for
every agent — and each policy's forward pass is one batched jitted call.

Shared vs independent policies both ride the same path: the mapping
function routes each agent's rows to a policy id; a shared policy simply
receives every agent's rows concatenated.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


class MultiAgentVectorEnv:
    """Vectorized multi-agent env with a FIXED agent set.

    Per-agent batched API (B = num sub-envs):
      reset_all(seed) -> {agent_id: [B, obs_dim]}
      step_batch({agent_id: [B]}) -> (obs_dict, reward_dict,
                                      terminated [B], truncated [B])
    Termination is per sub-env (all agents of one sub-env end together —
    the cooperative/competitive-game shape; reference MultiAgentEnv's
    "__all__" done flag).  Implementations auto-reset finished sub-envs.
    """

    agent_ids: Tuple[str, ...] = ()
    observation_dims: Dict[str, int] = {}
    num_actions_by_agent: Dict[str, int] = {}

    def __init__(self, num_envs: int):
        self.num_envs = num_envs
        self._ep_return = {a: np.zeros(num_envs, np.float64)
                           for a in self.agent_ids}
        self._ep_len = np.zeros(num_envs, np.int64)
        self.completed_returns: Dict[str, list] = {a: []
                                                   for a in self.agent_ids}
        self.completed_lengths: list = []

    def reset_all(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step_batch(self, actions: Dict[str, np.ndarray]):
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        obs, rew, term, trunc = self.step_batch(actions)
        for a in self.agent_ids:
            self._ep_return[a] += rew[a]
        self._ep_len += 1
        done = term | trunc
        if done.any():
            idx = np.nonzero(done)[0]
            for a in self.agent_ids:
                self.completed_returns[a].extend(
                    float(x) for x in self._ep_return[a][idx])
                self._ep_return[a][done] = 0.0
            self.completed_lengths.extend(
                int(x) for x in self._ep_len[idx])
            self._ep_len[done] = 0
        return obs, rew, term, trunc

    def drain_episode_metrics(self):
        rets = {a: self.completed_returns[a] for a in self.agent_ids}
        lens = self.completed_lengths
        self.completed_returns = {a: [] for a in self.agent_ids}
        self.completed_lengths = []
        return rets, lens


class CooperativeMatchEnv(MultiAgentVectorEnv):
    """Two-agent cooperative test env (stands in for the reference's
    two-agent debugging envs, rllib/examples/envs/).

    Each agent observes its own one-hot target (4 classes) and earns 1.0
    for matching it; if BOTH match in the same step, both earn a +0.5
    cooperation bonus — so an agent's attainable return depends on its
    partner learning too.  Episodes run 16 steps with fresh targets each
    step: random policy ~ per-agent return 16*(0.25 + 0.5*0.0625) = 4.5;
    both-optimal = 16*1.5 = 24.
    """

    agent_ids = ("a0", "a1")
    N_TARGETS = 4
    EP_LEN = 16

    observation_dims = {"a0": 4, "a1": 4}
    num_actions_by_agent = {"a0": 4, "a1": 4}

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        self._targets = {a: np.zeros(num_envs, np.int64)
                         for a in self.agent_ids}
        self._steps = np.zeros(num_envs, np.int64)

    def _roll_targets(self, mask=None):
        for a in self.agent_ids:
            fresh = self._rng.integers(0, self.N_TARGETS, self.num_envs)
            if mask is None:
                self._targets[a] = fresh
            else:
                self._targets[a] = np.where(mask, fresh, self._targets[a])

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for a in self.agent_ids:
            o = np.zeros((self.num_envs, self.N_TARGETS), np.float32)
            o[np.arange(self.num_envs), self._targets[a]] = 1.0
            out[a] = o
        return out

    def reset_all(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._roll_targets()
        self._steps[:] = 0
        for a in self.agent_ids:
            self._ep_return[a][:] = 0.0
        self._ep_len[:] = 0
        return self._obs()

    def step_batch(self, actions: Dict[str, np.ndarray]):
        hit = {a: (np.asarray(actions[a]) == self._targets[a])
               for a in self.agent_ids}
        both = hit["a0"] & hit["a1"]
        rew = {a: hit[a].astype(np.float32) + 0.5 * both.astype(np.float32)
               for a in self.agent_ids}
        self._steps += 1
        truncated = self._steps >= self.EP_LEN
        terminated = np.zeros(self.num_envs, bool)
        self._roll_targets()          # fresh targets every step
        if truncated.any():
            self._steps[truncated] = 0
        return self._obs(), rew, terminated, truncated


_MA_REGISTRY: Dict[str, Callable[..., MultiAgentVectorEnv]] = {
    "coop-match": CooperativeMatchEnv,
}


def register_multi_agent_env(name: str, creator) -> None:
    _MA_REGISTRY[name] = creator


def make_multi_agent_env(name_or_creator, num_envs: int,
                         seed: int = 0) -> MultiAgentVectorEnv:
    if callable(name_or_creator):
        return name_or_creator(num_envs, seed)
    if name_or_creator in _MA_REGISTRY:
        return _MA_REGISTRY[name_or_creator](num_envs, seed=seed)
    raise ValueError(f"unknown multi-agent env {name_or_creator!r}")


class MultiAgentBatch:
    """{policy_id: SampleBatch} + env step count (reference:
    sample_batch.py MultiAgentBatch)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self.count = env_steps

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
        out: Dict[str, List[SampleBatch]] = {}
        steps = 0
        for mb in batches:
            steps += mb.count
            for pid, b in mb.policy_batches.items():
                out.setdefault(pid, []).append(b)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs) for pid, bs in out.items()},
            steps)


class MultiAgentRolloutWorker:
    """Steps a multi-agent vector env with one JaxPolicy per policy id
    (reference: rollout_worker.py with a policy map, rollout_worker.py:166
    `policy_dict`), emitting a MultiAgentBatch per fragment."""

    def __init__(self, env: Any, *, num_envs: int = 8,
                 rollout_fragment_length: int = 64,
                 gamma: float = 0.99, lam: float = 0.95,
                 hidden=(64, 64), seed: int = 0,
                 policies: Optional[Dict[str, Any]] = None,
                 policy_mapping_fn: Optional[Callable[[str], str]] = None,
                 postprocess: bool = True):
        from ray_tpu.rllib.rollout_worker import _force_cpu_platform_if_worker
        _force_cpu_platform_if_worker()
        self.env = make_multi_agent_env(env, num_envs, seed=seed)
        self.num_envs = num_envs
        self.fragment_length = rollout_fragment_length
        self.gamma, self.lam = gamma, lam
        self.agent_ids = self.env.agent_ids
        self.policy_mapping_fn = policy_mapping_fn or (lambda aid: aid)
        pids = sorted({self.policy_mapping_fn(a) for a in self.agent_ids})
        if policies:
            unknown = set(pids) - set(policies)
            if unknown:
                raise ValueError(
                    f"policy_mapping_fn routes to undeclared policies "
                    f"{sorted(unknown)}; declared: {sorted(policies)}")
        self.policies: Dict[str, JaxPolicy] = {}
        for pid in pids:
            # Every agent mapped to `pid` must share obs/action spaces.
            agents = [a for a in self.agent_ids
                      if self.policy_mapping_fn(a) == pid]
            dims = {self.env.observation_dims[a] for a in agents}
            acts = {self.env.num_actions_by_agent[a] for a in agents}
            if len(dims) != 1 or len(acts) != 1:
                raise ValueError(
                    f"agents {agents} share policy {pid!r} but have "
                    f"mismatched spaces")
            self.policies[pid] = JaxPolicy(
                dims.pop(), acts.pop(), hidden,
                seed=seed + 17 * (1 + pids.index(pid)))
        self.obs = self.env.reset_all(seed)
        self._total_steps = 0

    # -- weights -----------------------------------------------------------
    def get_weights(self) -> Dict[str, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            if pid in self.policies:
                self.policies[pid].set_weights(w)

    # -- sampling ----------------------------------------------------------
    def sample(self) -> Tuple[MultiAgentBatch, Dict]:
        T, B = self.fragment_length, self.num_envs
        A = self.agent_ids
        obs_buf = {a: np.empty((T, B, self.env.observation_dims[a]),
                               np.float32) for a in A}
        act_buf = {a: np.empty((T, B), np.int32) for a in A}
        logp_buf = {a: np.empty((T, B), np.float32) for a in A}
        vf_buf = {a: np.empty((T, B), np.float32) for a in A}
        rew_buf = {a: np.empty((T, B), np.float32) for a in A}
        term_buf = np.empty((T, B), np.bool_)
        trunc_buf = np.empty((T, B), np.bool_)

        obs = self.obs
        for t in range(T):
            actions = {}
            for a in A:
                pol = self.policies[self.policy_mapping_fn(a)]
                acts, logp, vf, _ = pol.compute_actions(obs[a])
                actions[a] = acts
                obs_buf[a][t] = obs[a]
                act_buf[a][t] = acts
                logp_buf[a][t] = logp
                vf_buf[a][t] = vf
            obs, rew, term, trunc = self.env.step(actions)
            for a in A:
                rew_buf[a][t] = rew[a]
            term_buf[t] = term
            trunc_buf[t] = trunc
        self.obs = obs
        self._total_steps += T * B

        rets, lens = self.env.drain_episode_metrics()
        # Per-policy mean returns for the improvement gates; the scalar
        # episode metric folds all agents (cooperative sum / len(A)).
        per_agent = {a: rets[a] for a in A}
        pooled = [r for a in A for r in rets[a]]
        metrics = {"episode_returns": pooled, "episode_lengths": lens,
                   "per_agent_returns": per_agent,
                   "env_steps": T * B, "total_env_steps": self._total_steps}

        done = term_buf | trunc_buf
        flat = lambda x: x.reshape((T * B,) + x.shape[2:])
        per_policy: Dict[str, List[SampleBatch]] = {}
        for a in A:
            pol = self.policies[self.policy_mapping_fn(a)]
            _, _, boot_vf, _ = pol.compute_actions(self.obs[a])
            adv, targets = compute_gae(rew_buf[a], vf_buf[a], done,
                                       boot_vf, self.gamma, self.lam)
            b = SampleBatch({
                SampleBatch.OBS: flat(obs_buf[a]),
                SampleBatch.ACTIONS: flat(act_buf[a]),
                SampleBatch.ACTION_LOGP: flat(logp_buf[a]),
                SampleBatch.VF_PREDS: flat(vf_buf[a]),
                SampleBatch.ADVANTAGES: flat(adv),
                SampleBatch.VALUE_TARGETS: flat(targets),
            })
            per_policy.setdefault(self.policy_mapping_fn(a), []).append(b)
        batch = MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs)
             for pid, bs in per_policy.items()}, T * B)
        return batch, metrics

    def ping(self) -> bool:
        return True
