"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning.

Reference parity: rllib/algorithms/marwil/ (marwil.py + the torch policy's
loss) — exponentially advantage-weighted behavior cloning over logged
trajectories: L = -E[ exp(beta * A_hat / c) * log pi(a|s) ] + vf loss,
with A_hat = (monte-carlo return) - V(s) from a jointly-trained critic
and c a running estimate of the advantage scale.  beta = 0 degrades to
plain BC (the reference implements BC as MARWIL with beta=0).

Offline-first like BC here: trains from logged SampleBatches (JsonReader
/ DatasetReader); the jitted update runs actor and critic in one fused
step.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def compute_mc_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-row discounted Monte-Carlo return-to-go within each logged
    episode (episode boundaries = done rows)."""
    out = np.zeros(len(rewards), np.float32)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = rewards[i] + gamma * acc
        out[i] = acc
    return out


class MARWILConfig:
    def __init__(self):
        self.beta = 1.0            # 0 = BC
        self.vf_coeff = 1.0
        self.gamma = 0.99
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_epochs = 1
        self.model_hidden = (64, 64)
        self.max_weight = 20.0     # clip the exp advantage weight
        self.seed = 0


class MARWIL:
    def __init__(self, obs_dim: int, num_actions: int,
                 config: Optional[MARWILConfig] = None):
        import jax
        import optax

        from ray_tpu.rllib.models import make_model

        self.config = config or MARWILConfig()
        cfg = self.config
        init_params, self.apply = make_model(obs_dim, num_actions,
                                             cfg.model_hidden)
        self.params = init_params(jax.random.key(cfg.seed))
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)
        # c^2 running moment of squared advantages (reference:
        # marwil_torch_policy.py ma_adv_norm update).
        self.adv_norm_sq = 1.0
        apply = self.apply
        beta, vf_coeff, max_w = cfg.beta, cfg.vf_coeff, cfg.max_weight

        def loss(params, obs, actions, returns, adv_norm):
            import jax.numpy as jnp
            logits, values = apply(params, obs)
            adv = returns - values
            # The weight uses the CURRENT advantage but must not push
            # gradients through the critic into the actor term.
            w = jnp.minimum(
                jnp.exp(beta * jax.lax.stop_gradient(adv) / adv_norm),
                max_w)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[:, None].astype(jnp.int32), axis=1)[:, 0]
            policy_loss = (w * nll).mean()
            vf_loss = (adv ** 2).mean()
            return policy_loss + vf_coeff * vf_loss, (
                policy_loss, vf_loss, jax.lax.stop_gradient(adv))

        def step(params, opt_state, obs, actions, returns, adv_norm):
            (l, aux), grads = jax.value_and_grad(loss, has_aux=True)(
                params, obs, actions, returns, adv_norm)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l, aux

        self._step = jax.jit(step)

    def train_on(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp

        cfg = self.config
        obs = np.asarray(batch[SampleBatch.OBS], np.float32)
        actions = np.asarray(batch[SampleBatch.ACTIONS])
        rewards = np.asarray(batch[SampleBatch.REWARDS], np.float32)
        term = np.asarray(batch.get(SampleBatch.TERMINATEDS,
                                    np.zeros(len(obs))), bool)
        trunc = np.asarray(batch.get(SampleBatch.TRUNCATEDS,
                                     np.zeros(len(obs))), bool)
        if obs.ndim > 2:
            obs = obs.reshape(-1, obs.shape[-1])
            actions, rewards = actions.reshape(-1), rewards.reshape(-1)
            term, trunc = term.reshape(-1), trunc.reshape(-1)
        returns = compute_mc_returns(rewards, term | trunc, cfg.gamma)
        n = len(obs)
        last = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, cfg.train_batch_size):
                idx = perm[lo:lo + cfg.train_batch_size]
                c = float(np.sqrt(self.adv_norm_sq)) + 1e-8
                self.params, self.opt_state, l, aux = self._step(
                    self.params, self.opt_state, jnp.asarray(obs[idx]),
                    jnp.asarray(actions[idx]), jnp.asarray(returns[idx]),
                    c)
                policy_loss, vf_loss, adv = aux
                # EMA of E[A^2] (the reference's moving advantage norm).
                self.adv_norm_sq += 1e-2 * (
                    float(np.mean(np.asarray(adv) ** 2)) - self.adv_norm_sq)
                last = {"total_loss": float(l),
                        "policy_loss": float(policy_loss),
                        "vf_loss": float(vf_loss)}
        last["samples"] = n
        return last

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        logits, _ = self.apply(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))

    def get_weights(self):
        import jax
        return jax.device_get(self.params)
