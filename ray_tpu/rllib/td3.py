"""TD3: twin-delayed deep deterministic policy gradient.

Reference parity: rllib/algorithms/td3/ (td3.py — DDPG with the three
TD3 tricks: twin Q networks with min-target, delayed policy updates,
target-policy smoothing noise; Gaussian exploration noise on rollouts).
TPU-first shape mirrors sac.py: the critic step and the (delayed)
actor+polyak step are two jitted XLA programs over one train-state
pytree; the delay counter is the only host-side control flow.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.dqn import _to_transitions
from ray_tpu.rllib.models import make_deterministic_actor, make_q_network
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=TD3)
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.005
        self.policy_delay = 2              # critic updates per actor update
        self.target_noise = 0.2            # smoothing noise sigma (x scale)
        self.target_noise_clip = 0.5       # clip (x scale)
        self.exploration_noise = 0.1       # rollout noise sigma (x scale)
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1_500
        self.random_warmup_steps = 1_000
        self.train_batch_size = 256
        self.updates_per_step = 32
        self.model_hidden = (256, 256)


class _TD3State(NamedTuple):
    actor: Any
    actor_t: Any
    q1: Any
    q2: Any
    q1_t: Any
    q2_t: Any
    actor_opt: Any
    critic_opt: Any
    rng: jax.Array


class _TD3Learner:
    def __init__(self, obs_dim: int, action_dim: int, cfg: TD3Config,
                 action_low, action_high, seed: int):
        hidden = cfg.model_hidden
        init_actor, actor_apply = make_deterministic_actor(
            obs_dim, action_dim, hidden)
        init_q, q_apply = make_q_network(obs_dim, action_dim, hidden)
        k = jax.random.split(jax.random.key(seed), 3)
        actor = init_actor(k[0])
        q1, q2 = init_q(k[1]), init_q(k[2])
        scale = jnp.asarray((np.asarray(action_high)
                             - np.asarray(action_low)) / 2.0, jnp.float32)
        center = jnp.asarray((np.asarray(action_high)
                              + np.asarray(action_low)) / 2.0, jnp.float32)
        low = jnp.asarray(action_low, jnp.float32)
        high = jnp.asarray(action_high, jnp.float32)
        actor_tx = optax.adam(cfg.actor_lr)
        critic_tx = optax.adam(cfg.critic_lr)
        self.state = _TD3State(
            actor=actor, actor_t=actor, q1=q1, q2=q2, q1_t=q1, q2_t=q2,
            actor_opt=actor_tx.init(actor),
            critic_opt=critic_tx.init((q1, q2)),
            rng=jax.random.key(seed + 7))
        gamma, tau = cfg.gamma, cfg.tau
        noise_sigma = cfg.target_noise
        noise_clip = cfg.target_noise_clip
        self.num_updates = 0
        self._policy_delay = cfg.policy_delay

        def act(params, obs):
            return actor_apply(params, obs) * scale + center

        def critic_step(state: _TD3State, batch):
            rng, k_noise = jax.random.split(state.rng)
            # Target-policy smoothing: a' = clip(actor_t(s') + clipped
            # noise) — regularizes the Q target against sharp peaks.
            a_next = act(state.actor_t, batch["next_obs"])
            noise = jnp.clip(
                noise_sigma * scale * jax.random.normal(
                    k_noise, a_next.shape),
                -noise_clip * scale, noise_clip * scale)
            a_next = jnp.clip(a_next + noise, low, high)
            q_next = jnp.minimum(
                q_apply(state.q1_t, batch["next_obs"], a_next),
                q_apply(state.q2_t, batch["next_obs"], a_next))
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (
                    1.0 - batch["dones"].astype(jnp.float32)) * q_next)

            def critic_loss(qs):
                p1, p2 = qs
                e1 = q_apply(p1, batch["obs"], batch["actions"]) - target
                e2 = q_apply(p2, batch["obs"], batch["actions"]) - target
                return (e1 ** 2 + e2 ** 2).mean()

            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                (state.q1, state.q2))
            c_updates, critic_opt = critic_tx.update(
                c_grads, state.critic_opt, (state.q1, state.q2))
            q1, q2 = optax.apply_updates((state.q1, state.q2), c_updates)
            return state._replace(q1=q1, q2=q2, critic_opt=critic_opt,
                                  rng=rng), c_loss

        def actor_step(state: _TD3State, batch):
            def actor_loss(ap):
                a_pi = act(ap, batch["obs"])
                return -q_apply(state.q1, batch["obs"], a_pi).mean()

            a_loss, a_grads = jax.value_and_grad(actor_loss)(state.actor)
            a_updates, actor_opt = actor_tx.update(
                a_grads, state.actor_opt, state.actor)
            actor = optax.apply_updates(state.actor, a_updates)
            polyak = lambda t, s: jax.tree.map(
                lambda a, b: (1 - tau) * a + tau * b, t, s)
            return state._replace(
                actor=actor, actor_t=polyak(state.actor_t, actor),
                q1_t=polyak(state.q1_t, state.q1),
                q2_t=polyak(state.q2_t, state.q2),
                actor_opt=actor_opt), a_loss

        self._critic_step = jax.jit(critic_step)
        self._actor_step = jax.jit(actor_step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.state, c_loss = self._critic_step(self.state, jb)
        metrics = {"critic_loss": float(c_loss)}
        self.num_updates += 1
        if self.num_updates % self._policy_delay == 0:
            self.state, a_loss = self._actor_step(self.state, jb)
            metrics["actor_loss"] = float(a_loss)
        return metrics

    def get_weights(self):
        return jax.device_get(self.state.actor)

    def get_state(self):
        s = jax.device_get(self.state._replace(rng=None))
        return {"td3_state": s._asdict(), "num_updates": self.num_updates}

    def set_state(self, state):
        d = dict(state["td3_state"])
        d["rng"] = self.state.rng
        self.state = _TD3State(**jax.device_put(d))
        self.num_updates = state.get("num_updates", 0)


class TD3(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        if not self.continuous:
            raise ValueError("TD3 requires a continuous-action env")
        self.workers = WorkerSet(
            num_workers=cfg.num_rollout_workers,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, hidden=cfg.model_hidden, seed=cfg.seed,
                postprocess=False, policy_kind="deterministic_noise",
                exploration_noise=cfg.exploration_noise,
                random_warmup_steps=cfg.random_warmup_steps))
        probe = self.workers.local_worker.env
        self.learner = _TD3Learner(
            self.obs_dim, self.action_dim, cfg,
            probe.action_low, probe.action_high, cfg.seed)
        from ray_tpu.rllib.replay_buffer import ReplayBuffer
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self.workers.sync_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        """Reference: td3/ddpg training_step (generic off-policy loop) —
        sample -> store -> N TD updates w/ delayed policy -> broadcast."""
        cfg = self.config
        batches, metrics_list = self.workers.sample_sync()
        episodes = self._record_metrics(metrics_list)
        for b in batches:
            self.buffer.add(_to_transitions(b))

        learner_metrics: Dict[str, float] = {}
        updates = 0
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_step):
                m = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
                learner_metrics.update(m)
                updates += 1
            self.workers.sync_weights(self.learner.get_weights())

        return {"episodes_this_iter": episodes,
                "buffer_size": len(self.buffer),
                "learner_updates_total": self.learner.num_updates,
                "updates_this_iter": updates,
                **{f"learner/{k}": v for k, v in learner_metrics.items()}}

    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
