"""IMPALA: async actor-learner RL.

Reference parity: rllib/algorithms/impala/impala.py:509 (training_step:659
— async sampling queues feeding a learner thread, periodic weight
broadcast) + rllib/execution/learner_thread.py:17 (LearnerThread).
TPU-first differences: the V-trace correction + SGD step is one jitted XLA
program over time-major fragments, and the learner thread is the host-side
pipeline that keeps the chip fed while rollout actors run ahead
asynchronously.
"""

from __future__ import annotations

import queue
import threading
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.models import make_model
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.vtrace import vtrace
from ray_tpu.rllib.worker_set import WorkerSet


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.lr = 6e-4
        self.grad_clip = 40.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        self.broadcast_interval = 1       # updates between weight broadcasts
        self.learner_queue_size = 16
        self.min_updates_per_step = 1


class _VTraceLearner:
    """Single-fragment jitted V-trace SGD step over time-major batches."""

    def __init__(self, obs_dim: int, num_actions: int, cfg: IMPALAConfig,
                 hidden, seed: int, mesh=None):
        use_lstm = getattr(cfg, "use_lstm", False)
        apply_seq = apply_step = None
        if use_lstm:
            from ray_tpu.rllib.models import make_recurrent_model
            init_params, apply_step, apply_seq, _init_state = \
                make_recurrent_model(obs_dim, num_actions, hidden,
                                     getattr(cfg, "lstm_size", 64))
            self.apply = apply_seq
        else:
            init_params, self.apply = make_model(obs_dim, num_actions,
                                                 hidden)
        self.params = init_params(jax.random.key(seed))
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr, eps=1e-5))
        self.opt_state = self.tx.init(self.params)
        self.num_updates = 0
        self.mesh = (mesh if mesh is not None
                     and any(s > 1 for s in mesh.shape.values()) else None)

        gamma = cfg.gamma
        vf_coeff = cfg.vf_loss_coeff
        ent_coeff = cfg.entropy_coeff
        rho_bar, c_bar = cfg.clip_rho_threshold, cfg.clip_c_threshold
        # APPO (reference: rllib/algorithms/appo/appo.py — IMPALA's
        # architecture with PPO's clipped surrogate on V-trace
        # advantages): when clip_param is set, the policy loss becomes
        # the clipped importance-ratio surrogate vs the BEHAVIOR policy.
        clip_param = getattr(cfg, "clip_param", None)
        apply = self.apply

        def loss(params, batch):
            obs = batch[SampleBatch.OBS]      # [T, B, D] or [T, B, H, W, C]
            T, B = obs.shape[:2]
            if use_lstm:
                # Time-major V-trace fragments are the LSTM's native
                # layout: one masked-reset scan over the chunk
                # (reference: rnn_sequencing in the IMPALA learner).
                logits, values = apply_seq(
                    params, obs, batch["state_in"], batch["resets"])
                _, bootstrap_value, _ = apply_step(
                    params, batch["bootstrap_obs"],
                    batch["bootstrap_state"])
            else:
                logits, values = apply(
                    params, obs.reshape((T * B,) + obs.shape[2:]))
                logits = logits.reshape(T, B, -1)
                values = values.reshape(T, B)
                _, bootstrap_value = apply(params, batch["bootstrap_obs"])

            logp_all = jax.nn.log_softmax(logits)
            actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
            target_logp = jnp.take_along_axis(
                logp_all, actions[..., None], axis=-1)[..., 0]

            done = (batch[SampleBatch.TERMINATEDS]
                    | batch[SampleBatch.TRUNCATEDS]).astype(jnp.float32)
            discounts = gamma * (1.0 - done)
            vt = vtrace(batch[SampleBatch.ACTION_LOGP], target_logp,
                        batch[SampleBatch.REWARDS], discounts, values,
                        bootstrap_value, rho_bar, c_bar)

            if clip_param is not None:
                ratio = jnp.exp(target_logp
                                - batch[SampleBatch.ACTION_LOGP])
                adv = vt.pg_advantages
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
                pg_loss = -surr.mean()
            else:
                pg_loss = -(vt.pg_advantages * target_logp).mean()
            vf_loss = 0.5 * ((vt.vs - values) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"total_loss": total, "policy_loss": pg_loss,
                           "vf_loss": vf_loss, "entropy": entropy}

        def step(params, opt_state, batch):
            (_, metrics), grads = jax.value_and_grad(
                loss, has_aux=True)(params, batch)
            if self.mesh is not None:
                grads = jax.lax.pmean(grads, "data")
                metrics = jax.lax.pmean(metrics, "data")
            updates, opt_state = self.tx.update(updates=grads,
                                                state=opt_state,
                                                params=params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        if self.mesh is not None:
            # Data-parallel learner: fragments (the batch dim of the
            # time-major [T, B] batch) are sliced across the data axis;
            # V-trace is per-sequence so slicing columns is exact, and
            # the gradient pmean reconstructs the global batch gradient
            # (reference: LearnerGroup's DDP fleet, learner_group.py:51).
            from jax.sharding import PartitionSpec as P

            from ray_tpu.parallel.mesh import shard_map_compat
            k = self.mesh.shape["data"]

            def shard_step(params, opt_state, batch):
                idx = jax.lax.axis_index("data")

                def slice_cols(key, x):
                    axis = 0 if key == "bootstrap_obs" else 1
                    rows = x.shape[axis] // k
                    return jax.lax.dynamic_slice_in_dim(
                        x, idx * rows, rows, axis=axis)

                local = {key: slice_cols(key, v)
                         for key, v in batch.items()}
                return step(params, opt_state, local)

            step_fn = shard_map_compat(
                shard_step, self.mesh, (P(), P(), P()), (P(), P(), P()))
        else:
            step_fn = step
        # No donation: the learner thread updates params while the driver
        # thread concurrently reads them for weight broadcast — donating
        # would delete buffers out from under the reader.
        self._step = jax.jit(step_fn)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._step(
            self.params, self.opt_state, jbatch)
        self.num_updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def get_state(self):
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state):
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])


class LearnerThread(threading.Thread):
    """Consumes fragments from a queue, runs SGD continuously.

    Reference: rllib/execution/learner_thread.py:17.
    """

    def __init__(self, learner: _VTraceLearner, queue_size: int):
        super().__init__(daemon=True, name="impala-learner")
        self.learner = learner
        self.inqueue: queue.Queue = queue.Queue(maxsize=queue_size)
        self.last_metrics: Dict[str, float] = {}
        self.stopped = False
        self._error = None

    def run(self) -> None:
        while not self.stopped:
            batch = self.inqueue.get()
            if batch is None:
                return
            try:
                self.last_metrics = self.learner.update(batch)
            except Exception as e:  # surface in training_step
                self._error = e
                return

    def stop(self) -> None:
        self.stopped = True
        try:
            self.inqueue.put_nowait(None)
        except queue.Full:
            pass

    def check_error(self) -> None:
        if self._error is not None:
            raise self._error


class IMPALA(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        self.workers = WorkerSet(
            num_workers=max(cfg.num_rollout_workers, 1),
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, lam=cfg.lambda_,
                hidden=cfg.model_hidden, seed=cfg.seed,
                postprocess=False,
                **({"policy_kind": "recurrent",
                    "lstm_size": cfg.lstm_size}
                   if getattr(cfg, "use_lstm", False) else {})))
        self.learner = _VTraceLearner(
            self.obs_dim, self.num_actions, cfg, cfg.model_hidden, cfg.seed,
            mesh=cfg.learner_mesh)
        self.workers.sync_weights(self.learner.get_weights())
        self.learner_thread = LearnerThread(
            self.learner, cfg.learner_queue_size)
        self.learner_thread.start()
        self._inflight: Dict[Any, Any] = {}   # ref -> worker
        self._updates_at_broadcast = 0

    def _launch(self, worker) -> None:
        self._inflight[worker.sample.remote()] = worker

    def training_step(self) -> Dict[str, Any]:
        """Reference: impala.py:659 — async sample -> learner queue ->
        periodic broadcast."""
        cfg = self.config
        self.learner_thread.check_error()
        for w in self.workers.remote_workers:
            if w not in self._inflight.values():
                self._launch(w)

        updates_before = self.learner.num_updates
        fragments = 0
        episodes = 0
        # Drain until the learner has made progress this step.
        while (self.learner.num_updates - updates_before
               < cfg.min_updates_per_step):
            self.learner_thread.check_error()
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=10.0)
            if not ready:
                continue
            for ref in ready:
                worker = self._inflight.pop(ref)
                try:
                    batch, metrics = ray_tpu.get(ref)
                except Exception:
                    worker = self.workers.replace_worker(worker)
                    self._launch(worker)
                    continue
                episodes += self._record_metrics([metrics])
                fragments += 1
                # Bounded put with error polling: if the learner thread died
                # with the queue full, a bare put() would deadlock the
                # driver instead of surfacing the learner exception.
                while True:
                    self.learner_thread.check_error()
                    if self.learner_thread.stopped:
                        return {"fragments_this_iter": fragments,
                                "episodes_this_iter": episodes,
                                "learner_updates_total":
                                    self.learner.num_updates}
                    try:
                        self.learner_thread.inqueue.put(batch, timeout=1.0)
                        break
                    except queue.Full:
                        continue
                # Broadcast newest weights to the worker that just
                # delivered, then relaunch it (reference: per-worker
                # broadcast on result, impala.py broadcast_interval).
                if (self.learner.num_updates - self._updates_at_broadcast
                        >= cfg.broadcast_interval):
                    ref_w = ray_tpu.put(self.learner.get_weights())
                    worker.set_weights.remote(ref_w)
                    self._updates_at_broadcast = self.learner.num_updates
                self._launch(worker)

        self.workers.local_worker.set_weights(self.learner.get_weights())
        return {"fragments_this_iter": fragments,
                "episodes_this_iter": episodes,
                "learner_updates_total": self.learner.num_updates,
                **{f"learner/{k}": v
                   for k, v in self.learner_thread.last_metrics.items()}}

    def stop(self) -> None:
        self.learner_thread.stop()
        super().stop()

    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
