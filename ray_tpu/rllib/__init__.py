"""ray_tpu.rllib — the RL library: rollout-worker actor fleets + JAX learners.

Reference parity: /root/reference/rllib/ (Algorithm:
algorithms/algorithm.py:149, PPO: algorithms/ppo/ppo.py:343, IMPALA:
algorithms/impala/impala.py:509, RolloutWorker:
evaluation/rollout_worker.py:166, WorkerSet: evaluation/worker_set.py:79,
SampleBatch: policy/sample_batch.py) re-architected TPU-first: learners are
single jitted XLA programs (multi-chip via shard_map data-parallel
learners), rollouts are natively vectorized numpy envs on CPU actors.
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.env import (  # noqa: F401
    CartPoleVector,
    Env,
    PendulumVector,
    VectorEnv,
    make_vector_env,
    register_env,
)
from ray_tpu.rllib.a2c import A2C, A2CConfig  # noqa: F401
from ray_tpu.rllib.ars import ARS, ARSConfig  # noqa: F401
from ray_tpu.rllib.bandit import (  # noqa: F401
    LinTS,
    LinTSConfig,
    LinUCB,
    LinUCBConfig,
)
from ray_tpu.rllib.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, LearnerThread  # noqa: F401
from ray_tpu.rllib.learner import JaxLearner, ppo_loss  # noqa: F401
from ray_tpu.rllib.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.marwil import MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.offline import BC, BCConfig, JsonReader, JsonWriter  # noqa: F401
from ray_tpu.rllib.policy import JaxPolicy  # noqa: F401
from ray_tpu.rllib.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.qmix import QMix, QMixConfig, VDNConfig  # noqa: F401
from ray_tpu.rllib.rollout_worker import RolloutWorker  # noqa: F401
from ray_tpu.rllib.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae  # noqa: F401
from ray_tpu.rllib.vtrace import vtrace  # noqa: F401
from ray_tpu.rllib.worker_set import WorkerSet  # noqa: F401

__all__ = [
    "A2C", "A2CConfig", "BC", "BCConfig", "DQN", "DQNConfig",
    "JsonReader", "JsonWriter",
    "PrioritizedReplayBuffer", "ReplayBuffer",
    "Algorithm", "AlgorithmConfig", "CartPoleVector", "Env", "VectorEnv",
    "IMPALA", "IMPALAConfig", "JaxLearner", "JaxPolicy", "LearnerThread",
    "PPO", "PPOConfig", "PendulumVector", "RolloutWorker", "SAC",
    "SACConfig", "SampleBatch", "TD3", "TD3Config", "WorkerSet",
    "compute_gae", "make_vector_env", "ppo_loss", "register_env", "vtrace",
]
