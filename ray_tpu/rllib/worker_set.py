"""WorkerSet: the actor fleet of RolloutWorkers.

Reference parity: rllib/evaluation/worker_set.py:79 (sync_weights:384,
foreach_worker:676, async foreach:776) and the fault-tolerance behavior of
rllib/utils/actor_manager.py:189 (FaultTolerantActorManager): failed
workers are detected on RPC error, replaced, and the fleet keeps going.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch

logger = logging.getLogger("ray_tpu.rllib")


class WorkerSet:
    def __init__(self, *, num_workers: int, worker_kwargs: Dict[str, Any],
                 num_cpus_per_worker: float = 1,
                 restart_failed_workers: bool = True,
                 max_failed_rounds: int = 3,
                 worker_cls: type = RolloutWorker):
        # Ship registered env creators by value: remote worker processes
        # have a fresh registry, so a NAME would resolve there to whatever
        # that process's registry holds (or nothing) — shipping the
        # driver's creator keeps local and remote envs identical even when
        # a built-in name was re-registered.  (Reference ships creators
        # via tune registry + GCS KV.)
        from ray_tpu.rllib import env as env_mod
        from ray_tpu.rllib import multi_agent as ma_mod
        env = worker_kwargs.get("env")
        if isinstance(env, str) and env in env_mod._ENV_REGISTRY:
            worker_kwargs = dict(worker_kwargs,
                                 env=env_mod._ENV_REGISTRY[env])
        elif isinstance(env, str) and env in ma_mod._MA_REGISTRY:
            worker_kwargs = dict(worker_kwargs,
                                 env=ma_mod._MA_REGISTRY[env])
        self._worker_kwargs = worker_kwargs
        self._max_failed_rounds = max_failed_rounds
        self._consecutive_failed_rounds = 0
        self._num_cpus = num_cpus_per_worker
        self._restart = restart_failed_workers
        self._remote_cls = ray_tpu.remote(num_cpus=num_cpus_per_worker)(
            worker_cls)
        self._workers: List[Any] = [
            self._make_worker(i) for i in range(num_workers)]
        # The local worker evaluates and holds canonical weights alongside
        # the learner (reference: WorkerSet.local_worker()).
        self.local_worker = worker_cls(**worker_kwargs)

    def _make_worker(self, index: int):
        kwargs = dict(self._worker_kwargs)
        kwargs["seed"] = kwargs.get("seed", 0) + 1000 * (index + 1)
        return self._remote_cls.remote(**kwargs)

    @property
    def num_remote_workers(self) -> int:
        return len(self._workers)

    def sync_weights(self, weights: Optional[Any] = None) -> None:
        """Broadcast weights to every remote worker via one object-store put.

        Reference: worker_set.py:384 — weights go through the object store
        so the payload is stored once and each worker pulls it.
        """
        if weights is None:
            weights = self.local_worker.get_weights()
        else:
            self.local_worker.set_weights(weights)
        if not self._workers:
            return
        ref = ray_tpu.put(weights)
        self._foreach_with_recovery(lambda w: w.set_weights.remote(ref))

    def sample_sync(self) -> Tuple[List[SampleBatch], List[Dict]]:
        """One synchronous sampling round across all remote workers.

        Reference: rllib/execution/rollout_ops.py:21
        (synchronous_parallel_sample).  With zero remote workers, samples
        from the local worker (reference num_workers=0 mode).
        """
        if not self._workers:
            batch, metrics = self.local_worker.sample()
            return [batch], [metrics]
        results = self._foreach_with_recovery(lambda w: w.sample.remote())
        batches = [b for b, _ in results]
        metrics = [m for _, m in results]
        return batches, metrics

    def sample_async(self) -> List[Tuple[Any, Any]]:
        """Kick off sample() on every worker; returns [(worker, ref)]."""
        return [(w, w.sample.remote()) for w in self._workers]

    def foreach_worker(self, fn: Callable[[Any], Any]) -> List[Any]:
        return self._foreach_with_recovery(fn)

    def _foreach_with_recovery(self, fn) -> List[Any]:
        refs = [(i, fn(w)) for i, w in enumerate(self._workers)]
        results: List[Any] = []
        failed: List[int] = []
        last_error: Exception | None = None
        for i, ref in refs:
            try:
                results.append(ray_tpu.get(ref))
            except Exception as e:  # actor died: replace and continue
                logger.warning("rollout worker %d failed: %s", i, e)
                failed.append(i)
                last_error = e
        # A deterministic failure (bad env creator, unpicklable kwarg...)
        # would otherwise loop forever replacing dead workers: surface it
        # after max_failed_rounds rounds with zero survivors.
        if results or not refs:
            self._consecutive_failed_rounds = 0
        else:
            self._consecutive_failed_rounds += 1
            if self._consecutive_failed_rounds >= self._max_failed_rounds:
                raise RuntimeError(
                    f"all {len(refs)} rollout workers failed "
                    f"{self._consecutive_failed_rounds} rounds in a row; "
                    f"last error: {last_error!r}") from last_error
        if failed and self._restart:
            for i in failed:
                self._workers[i] = self._make_worker(i)
                try:
                    ref = ray_tpu.put(self.local_worker.get_weights())
                    ray_tpu.get(self._workers[i].set_weights.remote(ref))
                except Exception:
                    pass
        return results

    def replace_worker(self, worker) -> Any:
        """Replace a specific (failed) worker actor; returns the new one."""
        i = self._workers.index(worker)
        self._workers[i] = self._make_worker(i)
        return self._workers[i]

    def stop(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []

    @property
    def remote_workers(self) -> List[Any]:
        return list(self._workers)
