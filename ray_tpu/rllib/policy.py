"""JaxPolicy: action sampling + weight get/set, shared by workers and
learners.

Reference parity: rllib/policy/policy.py (compute_actions,
get_weights/set_weights) — reduced to the functional JAX shape: params are
a pytree, inference is one jitted pure function.
"""

from __future__ import annotations

import contextlib
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models import (
    gaussian_logp,
    make_continuous_model,
    make_model,
)


class JaxPolicy:
    """Categorical-action policy over an ActorCritic model.

    Inference is pinned to the host CPU backend by default: rollout
    policies are tiny, env stepping is CPU-bound, and a fleet of rollout
    actors must never contend for (or round-trip through) the TPU chip —
    the chip belongs to the learner.
    """

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64), seed: int = 0,
                 force_cpu: bool = True, action_dim: int = 0,
                 action_low: float = -1.0, action_high: float = 1.0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.continuous = num_actions == 0 and action_dim > 0
        self.action_dim = action_dim
        self._device = None
        if force_cpu and jax.default_backend() != "cpu":
            self._device = jax.local_devices(backend="cpu")[0]
        if self.continuous:
            init_params, self.apply = make_continuous_model(
                obs_dim, action_dim, hidden)

            def _sample(params, obs, rng):
                mean, log_std, value = self.apply(params, obs)
                noise = jax.random.normal(rng, mean.shape)
                action = mean + jnp.exp(log_std) * noise
                logp = gaussian_logp(mean, log_std, action)
                # Return the UNCLIPPED sample: the stored action and its
                # logp must describe the same point or the PPO ratio is
                # biased at the bounds; the env clips at step time.
                return action, logp, value, mean

            def _greedy(params, obs):
                mean, _log_std, value = self.apply(params, obs)
                return (jnp.clip(mean, action_low, action_high),
                        value, mean)
        else:
            init_params, self.apply = make_model(obs_dim, num_actions,
                                                 hidden)

            def _sample(params, obs, rng):
                logits, value = self.apply(params, obs)
                action = jax.random.categorical(rng, logits)
                logp = jax.nn.log_softmax(logits)[
                    jnp.arange(action.shape[0]), action]
                return action, logp, value, logits

            def _greedy(params, obs):
                logits, value = self.apply(params, obs)
                return jnp.argmax(logits, axis=-1), value, logits

        with self._ctx():
            self.params = init_params(jax.random.key(seed))
            self._rng = jax.random.key(seed + 1)
            self._sample = jax.jit(_sample)
            self._greedy = jax.jit(_greedy)

    def _ctx(self):
        if self._device is None:
            return contextlib.nullcontext()
        return jax.default_device(self._device)

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                   np.ndarray]:
        """Returns (actions, logp, vf_preds, logits) as numpy."""
        with self._ctx():
            # uint8 image obs ship as bytes (the conv model scales them
            # on-device); everything else is float32.
            if getattr(obs, "dtype", None) == np.uint8:
                obs = jnp.asarray(obs)
            else:
                obs = jnp.asarray(obs, jnp.float32)
            if explore:
                self._rng, sub = jax.random.split(self._rng)
                a, logp, v, logits = self._sample(self.params, obs, sub)
                return (np.asarray(a), np.asarray(logp), np.asarray(v),
                        np.asarray(logits))
            a, v, logits = self._greedy(self.params, obs)
            z = np.zeros(len(obs), np.float32)
            return np.asarray(a), z, np.asarray(v), np.asarray(logits)

    def value(self, obs: np.ndarray) -> np.ndarray:
        _, _, v, _ = self.compute_actions(obs)
        return v

    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, weights: Any) -> None:
        with self._ctx():
            self.params = jax.device_put(weights)


class _ContinuousRolloutPolicy:
    """Shared shell for off-policy continuous rollout policies: CPU-pinned
    jitted inference over an actor network, env-scale action output.
    compute_actions matches JaxPolicy's interface; logp/value slots are
    zeros (off-policy learners never consume them)."""

    def __init__(self, obs_dim: int, action_dim: int,
                 action_low: float, action_high: float,
                 force_cpu: bool = True):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.continuous = True
        self._device = None
        if force_cpu and jax.default_backend() != "cpu":
            self._device = jax.local_devices(backend="cpu")[0]
        self._scale = (np.asarray(action_high) - np.asarray(action_low)) / 2.0
        self._center = (np.asarray(action_high) + np.asarray(action_low)) / 2.0

    def _ctx(self):
        if self._device is None:
            return contextlib.nullcontext()
        return jax.default_device(self._device)

    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, weights: Any) -> None:
        with self._ctx():
            self.params = jax.device_put(weights)

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)


class SquashedGaussianRolloutPolicy(_ContinuousRolloutPolicy):
    """SAC behavior policy: a ~ tanh(mean + std*eps) scaled to env bounds
    (reference: rllib/algorithms/sac — SquashedGaussian distribution;
    exploration is the stochastic policy itself)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(256, 256),
                 seed: int = 0, action_low: float = -1.0,
                 action_high: float = 1.0, force_cpu: bool = True):
        super().__init__(obs_dim, action_dim, action_low, action_high,
                         force_cpu)
        from ray_tpu.rllib.models import make_squashed_actor
        init_params, self.apply = make_squashed_actor(
            obs_dim, action_dim, hidden)
        scale, center = self._scale, self._center

        def _sample(params, obs, rng):
            mean, log_std = self.apply(params, obs)
            u = mean + jnp.exp(log_std) * jax.random.normal(rng, mean.shape)
            return jnp.tanh(u) * scale + center, mean

        def _greedy(params, obs):
            mean, _ = self.apply(params, obs)
            return jnp.tanh(mean) * scale + center, mean

        with self._ctx():
            self.params = init_params(jax.random.key(seed))
            self._rng = jax.random.key(seed + 1)
            self._sample = jax.jit(_sample)
            self._greedy = jax.jit(_greedy)

    def compute_actions(self, obs: np.ndarray, explore: bool = True):
        with self._ctx():
            obs = jnp.asarray(obs, jnp.float32)
            if explore:
                self._rng, sub = jax.random.split(self._rng)
                a, mean = self._sample(self.params, obs, sub)
            else:
                a, mean = self._greedy(self.params, obs)
            z = np.zeros(len(obs), np.float32)
            return np.asarray(a), z, z, np.asarray(mean)


class DeterministicNoiseRolloutPolicy(_ContinuousRolloutPolicy):
    """TD3 behavior policy: a = clip(actor(s) + N(0, sigma*scale), bounds)
    (reference: rllib/algorithms/td3 — GaussianNoise exploration over a
    deterministic policy)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(256, 256),
                 seed: int = 0, action_low: float = -1.0,
                 action_high: float = 1.0, force_cpu: bool = True,
                 noise_scale: float = 0.1):
        super().__init__(obs_dim, action_dim, action_low, action_high,
                         force_cpu)
        from ray_tpu.rllib.models import make_deterministic_actor
        init_params, self.apply = make_deterministic_actor(
            obs_dim, action_dim, hidden)
        scale, center = self._scale, self._center
        low, high = action_low, action_high

        def _act(params, obs, rng, sigma):
            a = self.apply(params, obs) * scale + center
            noise = sigma * scale * jax.random.normal(rng, a.shape)
            return jnp.clip(a + noise, low, high), a

        with self._ctx():
            self.params = init_params(jax.random.key(seed))
            self._rng = jax.random.key(seed + 1)
            self._act = jax.jit(_act)
        self.noise_scale = noise_scale

    def compute_actions(self, obs: np.ndarray, explore: bool = True):
        with self._ctx():
            obs = jnp.asarray(obs, jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            sigma = self.noise_scale if explore else 0.0
            a, mean = self._act(self.params, obs, sub, sigma)
            z = np.zeros(len(obs), np.float32)
            return np.asarray(a), z, z, np.asarray(mean)


class RecurrentJaxPolicy:
    """LSTM actor-critic policy with explicit state threading
    (reference: rllib/policy — compute_actions' state_batches /
    get_initial_state).  compute_actions takes and returns the recurrent
    state; the rollout worker owns per-env state and resets it at episode
    boundaries."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64,), lstm_size: int = 64,
                 seed: int = 0, force_cpu: bool = True):
        from ray_tpu.rllib.models import make_recurrent_model
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.continuous = False
        self.lstm_size = lstm_size
        self._device = None
        if force_cpu and jax.default_backend() != "cpu":
            self._device = jax.local_devices(backend="cpu")[0]
        init_params, self.apply_step, self.apply_seq, self.initial_state \
            = make_recurrent_model(obs_dim, num_actions, hidden, lstm_size)

        def _sample(params, obs, state, rng):
            logits, value, state_out = self.apply_step(params, obs, state)
            action = jax.random.categorical(rng, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(action.shape[0]), action]
            return action, logp, value, logits, state_out

        def _greedy(params, obs, state):
            logits, value, state_out = self.apply_step(params, obs, state)
            return jnp.argmax(logits, axis=-1), value, logits, state_out

        with self._ctx():
            self.params = init_params(jax.random.key(seed))
            self._rng = jax.random.key(seed + 1)
            self._sample = jax.jit(_sample)
            self._greedy = jax.jit(_greedy)

    _ctx = JaxPolicy._ctx

    def compute_actions(self, obs: np.ndarray, state: np.ndarray,
                        explore: bool = True):
        """(actions, logp, vf, logits, state_out) — state is [2, B, H]."""
        with self._ctx():
            obs = jnp.asarray(obs, jnp.float32)
            state = jnp.asarray(state)
            if explore:
                self._rng, sub = jax.random.split(self._rng)
                a, logp, v, logits, s = self._sample(
                    self.params, obs, state, sub)
                return (np.asarray(a), np.asarray(logp), np.asarray(v),
                        np.asarray(logits), np.asarray(s))
            a, v, logits, s = self._greedy(self.params, obs, state)
            z = np.zeros(len(obs), np.float32)
            return (np.asarray(a), z, np.asarray(v), np.asarray(logits),
                    np.asarray(s))

    get_weights = JaxPolicy.get_weights
    set_weights = JaxPolicy.set_weights
